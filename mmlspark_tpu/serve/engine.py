"""``ServeEngine`` — the public continuous-batching serving API.

Turns the repo's static-shape KV-cache decode (``models/generate.py``)
into a multi-tenant engine: requests of different prompt lengths and
arrival times share ONE jitted decode program over the slot pool's
fixed-shape buffers. The decode program is a FUSED BLOCK
(``models.generate.make_decode_block``): ``lax.scan`` over up to
``decode_block`` greedy micro-steps inside one dispatch, sampling and
advancing per-slot positions on device, with an on-device live/EOS/
budget mask so finished slots emit pads without branching — ONE host
sync per block instead of one per token, which is what the per-token
latency of a dispatch-bound small-model tick is made of. Block sizes
are clamped to a power-of-two ladder, so at most
``num_decode_blocks`` = O(log decode_block) decode programs ever
compile (asserted by ``tests/test_serve.py`` via
``decode_compile_count``; the ladder shrinks near per-request budgets
to keep token-for-token parity with ``generate()``). Prefill is its own
jitted program, BUCKETED by prompt length: prompts right-pad to
power-of-two buckets, so at most O(log cache_len) prefill programs ever
compile (``prefill_compile_count`` <= ``num_prefill_buckets``) —
joiners pay a bucketed prefill, the steady-state decode tick never
recompiles. The block reads each slot's cache through the length-aware
split-KV kernel (``ops/flash_attention.flash_decode``, with dead rows'
live lengths zeroed mid-block) and DONATES the pool's buffer pytree
plus the device positions/live mask, so all decode state updates in
place on device (docs/SERVING.md has the donation contract).

Usage::

    engine = ServeEngine(graph, variables, slots=8)
    rid = engine.submit(prompt_ids, max_new_tokens=32)   # queued
    results = engine.run()                                # drain
    results[rid].tokens                                   # prompt + gen

``submit`` is admission-controlled (bounded queue raises the typed
:class:`FriendlyError` when full) and validates per-request budgets
against the pool's ``cache_len``; ``step()`` runs one scheduler tick
(admit -> fused decode -> retire) and returns the requests that finished
on it; ``run()`` loops ``step()`` until idle. Decode is greedy
(temperature-0) — identical tokens to ``generate()`` per request, which
is the engine's correctness contract.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.telemetry import (
    FlightRecorder,
    RetraceWatchdog,
    SpanTracer,
)
from mmlspark_tpu.models.generate import (
    _cached_apply,
    greedy_next,
    init_cache,
    make_decode_block,
)
from mmlspark_tpu.parallel.mesh import make_mesh, parse_mesh_axes
from mmlspark_tpu.parallel.sharding import (
    TRANSFORMER_TP_RULES,
    shard_params,
)
from mmlspark_tpu.serve.cache_pool import SlotCachePool
from mmlspark_tpu.serve.metrics import ServeMetrics
from mmlspark_tpu.serve.scheduler import (
    ContinuousBatchScheduler,
    RequestResult,
    ServeRequest,
)
from mmlspark_tpu.testing.compile_guard import (
    ProgramCountingJit,
    jit_cache_size,
)
from mmlspark_tpu.utils.profiling import annotate


def _resolve_mesh(mesh):
    """Engine ``mesh`` argument -> jax Mesh or None. Accepts a built
    Mesh, an axes mapping (``{"data": -1, "model": 2}``), or the CLI
    string spelling (``"data=4,model=2"``)."""
    if mesh is None:
        return None
    if isinstance(mesh, str):
        mesh = parse_mesh_axes(mesh)
    if isinstance(mesh, dict):
        return make_mesh(mesh)
    return mesh


class ServeEngine:
    def __init__(self, graph, variables, *, slots: int = 4,
                 cache_len: int | None = None, max_queue: int = 16,
                 pad_id: int = 0, decode_block: int = 32,
                 mesh=None,
                 recorder: FlightRecorder | None = None):
        if not graph.extra.get("causal", False):
            raise FriendlyError(
                f"serving needs a causal LM; '{graph.name}' has "
                "causal=False"
            )
        max_len = graph.input_shape[0] if graph.input_shape else None
        if cache_len is None:
            if not max_len:
                raise FriendlyError(
                    f"'{graph.name}' records no input_shape; pass "
                    "cache_len explicitly to size the slot KV buffers"
                )
            cache_len = max_len
        if (
            max_len
            and cache_len > max_len
            and graph.extra.get("pos_embedding", "learned") == "learned"
        ):
            raise FriendlyError(
                f"cache_len ({cache_len}) exceeds the learned position "
                f"table ({max_len}); build the model with a larger "
                "max_len or pos_embedding='rope'"
            )
        window = graph.extra.get("window")
        if window and window < cache_len:
            raise FriendlyError(
                f"'{graph.name}' uses a sliding window ({window}) "
                f"smaller than cache_len ({cache_len}); the slot pool "
                "holds linear per-slot buffers only — rolled circular "
                "buffers are not pooled yet. Serve with cache_len <= "
                "window, or build the model without window"
            )
        if decode_block < 1:
            raise FriendlyError(
                f"decode_block must be >= 1, got {decode_block} "
                "(1 = per-token dispatch, larger fuses T micro-steps "
                "into one device program)"
            )
        self.graph = graph
        self.pad_id = pad_id
        self.cache_len = cache_len
        # floor to a power of two: block sizes live on the ladder
        # {1, 2, 4, ..., decode_block}, so the scan-length static arg
        # compiles O(log) program variants, never one per budget
        self.decode_block = 1 << (int(decode_block).bit_length() - 1)
        # sharded serving (docs/SERVING.md "Sharded serving"): with a
        # mesh, params commit to the model axis by the Megatron rules
        # and the pool's slot-batched state to the data axis; GSPMD
        # partitions the SAME prefill/decode programs — XLA inserts the
        # collectives, token streams stay bit-identical to the
        # single-device engine, and the compile-count pins hold because
        # every per-tick input is committed to a fixed NamedSharding
        self.mesh = _resolve_mesh(mesh)
        self.variables = (
            shard_params(variables, self.mesh, TRANSFORMER_TP_RULES)
            if self.mesh is not None else variables
        )
        self.pool = SlotCachePool(graph, variables, slots, cache_len,
                                  mesh=self.mesh)
        self.metrics = ServeMetrics(
            graph.name, slots, decode_block=self.decode_block,
            mesh_shape=(
                {k: int(v) for k, v in self.mesh.shape.items()}
                if self.mesh is not None else {}
            ),
            mesh_devices=(
                int(self.mesh.size) if self.mesh is not None else 1
            ),
            cache_pool_bytes_per_device=(
                self.pool.device_bytes_per_device()
            ),
        )
        #: flight recorder (core/telemetry): one span per request
        #: lifecycle — queued -> admitted -> prefill[bucket] -> decode
        #: ticks -> finished/expired — dumpable as events.jsonl via the
        #: CLI's ``--telemetry-dir`` (docs/OBSERVABILITY.md)
        self.recorder = recorder if recorder is not None else FlightRecorder()
        self._tracer = SpanTracer(self.recorder)
        self._spans: dict[int, object] = {}
        self._sched = ContinuousBatchScheduler(self.pool,
                                               max_queue=max_queue)
        self._next_id = 0

        # bucketed prefill: prompts are right-padded to power-of-two
        # length buckets, so the prefill program count is O(log
        # cache_len) instead of O(distinct prompt lengths). Causality
        # makes the pads invisible: pad positions sit AFTER every real
        # token, the real positions' K/V and logits cannot see them, and
        # ``last`` (traced, so no retrace per value) slices the true
        # last-token logits out of the padded row. MoE models opt out —
        # their expert-capacity routing is not causal (a pad consumes
        # capacity that can change a REAL token's expert), so they keep
        # exact-length prefill.
        self._bucketed = not graph.extra.get("n_experts")

        def _prefill(variables, prompt, last):
            # (1, B) padded prompt -> first greedy token (from position
            # ``last``, the true prompt end) + a length-B linear cache;
            # jit retraces per distinct BUCKET
            cache = init_cache(graph, variables, 1, prompt.shape[1])
            logits, cache = _cached_apply(graph, variables, prompt,
                                          cache, 0)
            cur = jax.lax.dynamic_slice_in_dim(
                logits, last, 1, axis=1
            )[:, 0]
            return greedy_next(cur), cache

        # both programs run behind the retrace watchdog: any compile
        # beyond the design's budget (decode: one per ladder block
        # size, prefill: one per bucket) is logged the moment it
        # happens with the abstract shapes that triggered it, and lands
        # in the flight recorder's event timeline next to the request
        # that caused it
        # ProgramCountingJit makes the counts true XLA-program counts
        # even under a mesh, where jax's raw signature cache would
        # re-register NamedSharding-committed args as "new shapes"
        # (testing/compile_guard.py) — the pins and watchdog budgets
        # therefore hold unchanged on sharded engines
        self._prefill = RetraceWatchdog(
            ProgramCountingJit(jax.jit(_prefill)), "serve.prefill",
            registry=self.metrics.registry, recorder=self.recorder,
            expected_programs=self.num_prefill_buckets,
        )
        # the FUSED decode block (models.generate.make_decode_block):
        # lax.scan over t greedy micro-steps with the scan length
        # static (one program per ladder size) and the whole device
        # decode state DONATED — the slot-pool cache pytree AND the
        # per-slot positions/live mask update in place on device.
        # Contract: the engine immediately rebinds pool.buffers/
        # positions/live to the block's outputs and nothing else may
        # hold the donated references (docs/SERVING.md).
        # under a mesh the block's loop-carried outputs are PINNED to
        # the pool's canonical shardings (out_shardings): tick N's
        # outputs re-enter tick N+1 with byte-identical placement, so
        # the signature reaches its fixed point on the first call and
        # the ladder pins hold — GSPMD would otherwise pick output
        # shardings of its own and every tick would re-register
        jit_kwargs = {}
        if self.mesh is not None:
            slot_sh = self.pool.slot_sharding
            jit_kwargs["out_shardings"] = (
                slot_sh, slot_sh, self.pool.kv_shardings, slot_sh,
            )
        self._decode = RetraceWatchdog(
            ProgramCountingJit(jax.jit(
                make_decode_block(graph, pad_id),
                static_argnums=(7,), donate_argnums=(1, 2, 3),
                **jit_kwargs,
            )),
            "serve.decode",
            registry=self.metrics.registry, recorder=self.recorder,
            expected_programs=self.num_decode_blocks,
        )

    # -- prefill buckets ---------------------------------------------------

    def prefill_bucket(self, prompt_len: int) -> int:
        """Padded length the prefill program runs at for a prompt of
        ``prompt_len``: the next power of two >= max(prompt_len, 8),
        capped at ``cache_len`` (admission control guarantees
        prompt_len < cache_len, so the cap always covers the prompt).
        MoE engines bucket at exact length (see ``__init__``)."""
        if not self._bucketed:
            return prompt_len
        bucket = 8
        while bucket < prompt_len:
            bucket *= 2
        return min(bucket, self.cache_len)

    @property
    def num_prefill_buckets(self) -> int:
        """How many distinct prefill programs CAN exist for this engine
        — the ceiling the compile-guard tests pin prefill to."""
        return len({
            self.prefill_bucket(p) for p in range(1, self.cache_len)
        })

    # -- decode-block ladder ----------------------------------------------

    def _block_size(self, min_rem: int) -> int:
        """This tick's fused-block scan length: the largest ladder power
        of two <= min(decode_block, minimum remaining budget over active
        slots). Clamping to the min budget is the "shrink near budgets"
        parity rule: no slot can overrun its budget mid-block, so budget
        exhaustion only ever lands exactly on a block boundary (the only
        mid-block death is EOS, which the on-device mask handles)."""
        cap = min(self.decode_block, max(1, min_rem))
        t = 1
        while t * 2 <= cap:
            t *= 2
        return t

    @property
    def num_decode_blocks(self) -> int:
        """How many distinct fused decode-block programs CAN exist for
        this engine — one per ladder size T in {1, 2, 4, ...,
        decode_block}, the ceiling the compile-guard tests pin decode
        to. Scan iterations inside a block share one program; only
        distinct static scan lengths compile separately."""
        return self.decode_block.bit_length()

    # -- introspection -----------------------------------------------------

    @property
    def tick(self) -> int:
        return self._sched.tick_count

    @property
    def queue_depth(self) -> int:
        return self._sched.queue_depth

    @property
    def busy(self) -> bool:
        return self._sched.busy

    @property
    def decode_compile_count(self) -> int:
        """How many DISTINCT XLA programs the fused decode block has
        compiled — one per ladder size actually run, never more than
        ``num_decode_blocks`` for the life of the engine (asserted in
        tests; the retrace watchdog logs any violation live with the
        triggering shapes). Scan iterations do NOT count: a T=32 block
        is one program, not 32."""
        return jit_cache_size(self._decode)

    @property
    def prefill_compile_count(self) -> int:
        """How many prefill programs have compiled — bounded by
        ``num_prefill_buckets`` for the life of the engine (asserted in
        tests), however many distinct prompt lengths arrive."""
        return jit_cache_size(self._prefill)

    # -- public API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: int | None = None,
               deadline_ticks: int | None = None) -> int:
        """Queue one request; returns its id. Raises
        :class:`FriendlyError` on invalid budgets or a full queue
        (admission control) — never a bare KeyError/ValueError.

        ``deadline_ticks``: the request must FINISH within that many
        scheduler ticks of submission or it expires (queued or
        mid-decode), surfacing as status ``"expired"``.
        """
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise FriendlyError(
                f"prompt must be a non-empty 1-D token vector, got "
                f"shape {prompt.shape} (the engine serves one request "
                "per submit; batch by submitting several)"
            )
        if max_new_tokens < 1:
            raise FriendlyError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        total = int(prompt.size) + max_new_tokens
        if total > self.cache_len:
            raise FriendlyError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's cache_len "
                f"({self.cache_len}); shorten the request or build the "
                "engine with a larger cache_len"
            )
        if deadline_ticks is not None and deadline_ticks < 1:
            raise FriendlyError(
                f"deadline_ticks must be >= 1, got {deadline_ticks}"
            )
        req = ServeRequest(
            id=self._next_id,
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_id=eos_id,
            deadline_tick=(
                self.tick + deadline_ticks
                if deadline_ticks is not None else None
            ),
            submit_tick=self.tick,
            submit_wall=time.perf_counter(),
        )
        try:
            self._sched.enqueue(req)
        except FriendlyError:
            self.metrics.record_reject()
            self.recorder.record(
                "rejected", tick=self.tick, prompt_len=int(prompt.size),
                reason="queue_full",
            )
            raise
        self._next_id += 1
        self.metrics.record_submit()
        span = self._tracer.span(
            "request", tick=self.tick, id=req.id,
            prompt_len=int(prompt.size), max_new_tokens=max_new_tokens,
        )
        span.event("queued", tick=self.tick, queue_depth=self.queue_depth)
        self._spans[req.id] = span
        return req.id

    def step(self) -> list[RequestResult]:
        """One scheduler tick: expire deadlines, admit queued requests
        into free slots (prefill per joiner), ONE fused decode block of
        up to ``decode_block`` tokens for all active slots, retire
        finished sequences. Admission and retirement happen at block
        boundaries; the single host sync per tick fetches the whole
        ``(S, T)`` token block plus the finished vector. Returns the
        requests that reached a terminal state this tick."""
        t0 = time.perf_counter()
        tick = self._sched.tick_count
        finished = self._sched.expire(tick)
        tokens_this_tick = 0

        with annotate("serve.admit"):
            while self._sched.queue_depth and self.pool.free_count:
                req = self._sched.pop_next()
                slot = self.pool.lease()
                span = self._spans.get(req.id)
                if span is not None:
                    span.event("admitted", tick=tick, slot=slot)
                with annotate("serve.prefill"):
                    p = len(req.prompt)
                    bucket = self.prefill_bucket(p)
                    padded = np.full((bucket,), self.pad_id, np.int32)
                    padded[:p] = req.prompt
                    tp = time.perf_counter()
                    first, cache = self._prefill(
                        self.variables, jnp.asarray(padded[None]), p - 1
                    )
                    # only the REAL prompt's K/V enter the slot; the pad
                    # tail of the bucket cache is dropped here
                    self.pool.write_prefill(slot, cache, p)
                    first = int(first[0])
                if span is not None:
                    span.event(
                        "prefill", tick=tick, bucket=bucket,
                        ms=round((time.perf_counter() - tp) * 1e3, 3),
                    )
                self.metrics.record_first_token(req, tick, bucket=bucket)
                tokens_this_tick += 1
                done = self._sched.activate(slot, req, first, tick)
                if done is not None:
                    finished.append(done)

        # slot occupancy AS OF the decode dispatch: with fused blocks a
        # request can join and retire inside one tick, so sampling after
        # retirement would report empty slots that were busy all block
        leased_this_tick = self.pool.leased_count

        if self._sched.active:
            n_active = len(self._sched.active)
            states = list(self._sched.active.items())
            # write positions BEFORE the block: consume() advances the
            # host mirrors, and the live-KV accounting below needs the
            # per-slot starting frontier
            pre_pos = {slot: st.pos for slot, st in states}
            tok, rem, eos, min_rem = self._sched.decode_block_inputs(
                self.pad_id
            )
            t_block = self._block_size(min_rem)
            if self.mesh is not None:
                # commit the host-built per-tick vectors to the data
                # axis (device_put: a scatter, NOT a host sync) so every
                # tick presents the decode block one fixed signature
                slot_sh = self.pool.slot_sharding
                tok_d = jax.device_put(jnp.asarray(tok), slot_sh)
                rem_d = jax.device_put(jnp.asarray(rem), slot_sh)
                eos_d = jax.device_put(jnp.asarray(eos), slot_sh)
            else:
                tok_d, rem_d, eos_d = (
                    jnp.asarray(tok), jnp.asarray(rem), jnp.asarray(eos)
                )
            with annotate("serve.decode"):
                td = time.perf_counter()
                toks, live, buffers, positions = self._decode(
                    self.variables, self.pool.buffers,
                    self.pool.positions, self.pool.live,
                    tok_d, rem_d, eos_d, t_block,
                )
                # the inputs were DONATED: rebind the pool's device
                # state (buffers AND positions/live) to the block's
                # outputs before anything can touch stale references
                self.pool.buffers = buffers
                self.pool.positions = positions
                self.pool.live = live
                # the ONE host sync per block: (S, T) tokens + the
                # per-slot finished vector come back together
                toks_h, live_h = jax.device_get((toks, live))
                decode_s = time.perf_counter() - td
            blk_finished, consumed = self._sched.consume(toks_h, tick)
            n_tokens = sum(consumed.values())
            tokens_this_tick += n_tokens
            # live KV rows the block actually attended, per slot: its
            # c consumed micro-steps read frontiers pos0+1 .. pos0+c
            # (an arithmetic series) — vs the c * cache_len rows a
            # dense read would touch, the FLOP-utilization figure
            live_kv = sum(
                c * (pre_pos[slot] + 1) + c * (c - 1) // 2
                for slot, c in consumed.items()
            )
            self.metrics.record_decode(
                n_active, decode_s, tokens_emitted=n_tokens,
                block=t_block, live_kv=live_kv, cache_len=self.cache_len,
            )
            if __debug__:
                # the device live mask and the host's retirement
                # bookkeeping must agree slot for slot — the parity
                # contract's cheap runtime cross-check
                for slot, _st in states:
                    assert bool(live_h[slot]) == (
                        slot in self._sched.active
                    ), (
                        f"device live mask and host retirement disagree "
                        f"for slot {slot} (block T={t_block})"
                    )
            decode_ms = round(decode_s * 1e3, 3)
            for slot, st in states:
                span = self._spans.get(st.req.id)
                if span is not None:
                    span.event("decode", tick=tick, pos=pre_pos[slot],
                               n_active=n_active, block=t_block,
                               tokens=consumed.get(slot, 0),
                               step_ms=decode_ms)
            finished.extend(blk_finished)

        self._sched.tick_count += 1
        self.metrics.sample_tick(
            self._sched.queue_depth, leased_this_tick,
            time.perf_counter() - t0, tokens_emitted=tokens_this_tick,
        )
        for res in finished:
            self.metrics.record_finish(res)
            span = self._spans.pop(res.id, None)
            if span is not None:
                span.end(res.status, tick=res.finish_tick,
                         generated=res.generated)
        return finished

    def run(self, max_ticks: int = 100_000) -> dict[int, RequestResult]:
        """Step until queue and slots drain; results keyed by request
        id. ``max_ticks`` bounds runaway loops (a generator that never
        emits EOS still retires at its token budget, so hitting the
        bound means a caller bug — reported as the typed error)."""
        results: dict[int, RequestResult] = {}
        start = self.tick
        # black-box contract: the flight recorder dumps its last N
        # events to the error log automatically when the typed error
        # escapes — the post-mortem for "what was the engine doing"
        with self.recorder.dump_on_friendly_error():
            while self._sched.busy:
                if self.tick - start >= max_ticks:
                    raise FriendlyError(
                        f"serve run() exceeded max_ticks ({max_ticks}) "
                        f"with {self._sched.queue_depth} queued and "
                        f"{len(self._sched.active)} active requests"
                    )
                for res in self.step():
                    results[res.id] = res
        return results
