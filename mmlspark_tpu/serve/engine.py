"""``ServeEngine`` — the public continuous-batching serving API.

Turns the repo's static-shape KV-cache decode (``models/generate.py``)
into a multi-tenant engine: requests of different prompt lengths and
arrival times share ONE jitted decode step over the slot pool's
fixed-shape buffers, so XLA compiles the decode program exactly once per
engine (asserted by ``tests/test_serve.py`` via
``decode_compile_count``). Prefill is its own jitted program, BUCKETED
by prompt length: prompts right-pad to power-of-two buckets, so at most
O(log cache_len) prefill programs ever compile
(``prefill_compile_count`` <= ``num_prefill_buckets``) — joiners pay a
bucketed prefill, the steady-state decode tick never recompiles. The
decode step reads each slot's cache through the length-aware split-KV
kernel (``ops/flash_attention.flash_decode``) and DONATES the pool's
buffer pytree, so K/V update in place on device (docs/SERVING.md has
the donation contract).

Usage::

    engine = ServeEngine(graph, variables, slots=8)
    rid = engine.submit(prompt_ids, max_new_tokens=32)   # queued
    results = engine.run()                                # drain
    results[rid].tokens                                   # prompt + gen

``submit`` is admission-controlled (bounded queue raises the typed
:class:`FriendlyError` when full) and validates per-request budgets
against the pool's ``cache_len``; ``step()`` runs one scheduler tick
(admit -> fused decode -> retire) and returns the requests that finished
on it; ``run()`` loops ``step()`` until idle. Decode is greedy
(temperature-0) — identical tokens to ``generate()`` per request, which
is the engine's correctness contract.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.telemetry import (
    FlightRecorder,
    RetraceWatchdog,
    SpanTracer,
)
from mmlspark_tpu.models.generate import _cached_apply, init_cache
from mmlspark_tpu.serve.cache_pool import SlotCachePool
from mmlspark_tpu.serve.metrics import ServeMetrics
from mmlspark_tpu.serve.scheduler import (
    ContinuousBatchScheduler,
    RequestResult,
    ServeRequest,
)
from mmlspark_tpu.testing.compile_guard import jit_cache_size
from mmlspark_tpu.utils.profiling import annotate


class ServeEngine:
    def __init__(self, graph, variables, *, slots: int = 4,
                 cache_len: int | None = None, max_queue: int = 16,
                 pad_id: int = 0, recorder: FlightRecorder | None = None):
        if not graph.extra.get("causal", False):
            raise FriendlyError(
                f"serving needs a causal LM; '{graph.name}' has "
                "causal=False"
            )
        max_len = graph.input_shape[0] if graph.input_shape else None
        if cache_len is None:
            if not max_len:
                raise FriendlyError(
                    f"'{graph.name}' records no input_shape; pass "
                    "cache_len explicitly to size the slot KV buffers"
                )
            cache_len = max_len
        if (
            max_len
            and cache_len > max_len
            and graph.extra.get("pos_embedding", "learned") == "learned"
        ):
            raise FriendlyError(
                f"cache_len ({cache_len}) exceeds the learned position "
                f"table ({max_len}); build the model with a larger "
                "max_len or pos_embedding='rope'"
            )
        window = graph.extra.get("window")
        if window and window < cache_len:
            raise FriendlyError(
                f"'{graph.name}' uses a sliding window ({window}) "
                f"smaller than cache_len ({cache_len}); the slot pool "
                "holds linear per-slot buffers only — rolled circular "
                "buffers are not pooled yet. Serve with cache_len <= "
                "window, or build the model without window"
            )
        self.graph = graph
        self.variables = variables
        self.pad_id = pad_id
        self.cache_len = cache_len
        self.pool = SlotCachePool(graph, variables, slots, cache_len)
        self.metrics = ServeMetrics(graph.name, slots)
        #: flight recorder (core/telemetry): one span per request
        #: lifecycle — queued -> admitted -> prefill[bucket] -> decode
        #: ticks -> finished/expired — dumpable as events.jsonl via the
        #: CLI's ``--telemetry-dir`` (docs/OBSERVABILITY.md)
        self.recorder = recorder if recorder is not None else FlightRecorder()
        self._tracer = SpanTracer(self.recorder)
        self._spans: dict[int, object] = {}
        self._sched = ContinuousBatchScheduler(self.pool,
                                               max_queue=max_queue)
        self._next_id = 0

        # bucketed prefill: prompts are right-padded to power-of-two
        # length buckets, so the prefill program count is O(log
        # cache_len) instead of O(distinct prompt lengths). Causality
        # makes the pads invisible: pad positions sit AFTER every real
        # token, the real positions' K/V and logits cannot see them, and
        # ``last`` (traced, so no retrace per value) slices the true
        # last-token logits out of the padded row. MoE models opt out —
        # their expert-capacity routing is not causal (a pad consumes
        # capacity that can change a REAL token's expert), so they keep
        # exact-length prefill.
        self._bucketed = not graph.extra.get("n_experts")

        def _prefill(variables, prompt, last):
            # (1, B) padded prompt -> first greedy token (from position
            # ``last``, the true prompt end) + a length-B linear cache;
            # jit retraces per distinct BUCKET
            cache = init_cache(graph, variables, 1, prompt.shape[1])
            logits, cache = _cached_apply(graph, variables, prompt,
                                          cache, 0)
            cur = jax.lax.dynamic_slice_in_dim(
                logits, last, 1, axis=1
            )[:, 0]
            first = jnp.argmax(cur.astype(jnp.float32), axis=-1)
            return first.astype(jnp.int32), cache

        def _decode(variables, buffers, tok, pos):
            # ONE fused single-token step for every slot: tok/pos are
            # (S,) and every slot decodes at its own absolute position
            # (per-row live lengths through ops/flash_attention.py's
            # flash_decode — work per row scales with its live tokens,
            # not cache_len). Fixed shapes -> compiled exactly once.
            logits, buffers = _cached_apply(
                graph, variables, tok[:, None], buffers, pos, step=True
            )
            nxt = jnp.argmax(logits[:, 0].astype(jnp.float32), axis=-1)
            return nxt.astype(jnp.int32), buffers

        # both programs run behind the retrace watchdog: any compile
        # beyond the design's budget (decode: 1, prefill: one per
        # bucket) is logged the moment it happens with the abstract
        # shapes that triggered it, and lands in the flight recorder's
        # event timeline next to the request that caused it
        self._prefill = RetraceWatchdog(
            jax.jit(_prefill), "serve.prefill",
            registry=self.metrics.registry, recorder=self.recorder,
        )
        # the slot-pool cache pytree is DONATED through the decode step:
        # K/V buffers update in place on device instead of being copied
        # each tick. Contract: the engine immediately rebinds
        # ``pool.buffers`` to the step's outputs and nothing else may
        # hold the donated references (docs/SERVING.md).
        self._decode = RetraceWatchdog(
            jax.jit(_decode, donate_argnums=(1,)), "serve.decode",
            registry=self.metrics.registry, recorder=self.recorder,
        )

    # -- prefill buckets ---------------------------------------------------

    def prefill_bucket(self, prompt_len: int) -> int:
        """Padded length the prefill program runs at for a prompt of
        ``prompt_len``: the next power of two >= max(prompt_len, 8),
        capped at ``cache_len`` (admission control guarantees
        prompt_len < cache_len, so the cap always covers the prompt).
        MoE engines bucket at exact length (see ``__init__``)."""
        if not self._bucketed:
            return prompt_len
        bucket = 8
        while bucket < prompt_len:
            bucket *= 2
        return min(bucket, self.cache_len)

    @property
    def num_prefill_buckets(self) -> int:
        """How many distinct prefill programs CAN exist for this engine
        — the ceiling the compile-guard tests pin prefill to."""
        return len({
            self.prefill_bucket(p) for p in range(1, self.cache_len)
        })

    # -- introspection -----------------------------------------------------

    @property
    def tick(self) -> int:
        return self._sched.tick_count

    @property
    def queue_depth(self) -> int:
        return self._sched.queue_depth

    @property
    def busy(self) -> bool:
        return self._sched.busy

    @property
    def decode_compile_count(self) -> int:
        """How many programs the fused decode step has compiled — the
        continuous-batching invariant says this stays 1 for the life of
        the engine (asserted in tests; the retrace watchdog logs any
        violation live with the triggering shapes)."""
        return jit_cache_size(self._decode)

    @property
    def prefill_compile_count(self) -> int:
        """How many prefill programs have compiled — bounded by
        ``num_prefill_buckets`` for the life of the engine (asserted in
        tests), however many distinct prompt lengths arrive."""
        return jit_cache_size(self._prefill)

    # -- public API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: int | None = None,
               deadline_ticks: int | None = None) -> int:
        """Queue one request; returns its id. Raises
        :class:`FriendlyError` on invalid budgets or a full queue
        (admission control) — never a bare KeyError/ValueError.

        ``deadline_ticks``: the request must FINISH within that many
        scheduler ticks of submission or it expires (queued or
        mid-decode), surfacing as status ``"expired"``.
        """
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise FriendlyError(
                f"prompt must be a non-empty 1-D token vector, got "
                f"shape {prompt.shape} (the engine serves one request "
                "per submit; batch by submitting several)"
            )
        if max_new_tokens < 1:
            raise FriendlyError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        total = int(prompt.size) + max_new_tokens
        if total > self.cache_len:
            raise FriendlyError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's cache_len "
                f"({self.cache_len}); shorten the request or build the "
                "engine with a larger cache_len"
            )
        if deadline_ticks is not None and deadline_ticks < 1:
            raise FriendlyError(
                f"deadline_ticks must be >= 1, got {deadline_ticks}"
            )
        req = ServeRequest(
            id=self._next_id,
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_id=eos_id,
            deadline_tick=(
                self.tick + deadline_ticks
                if deadline_ticks is not None else None
            ),
            submit_tick=self.tick,
            submit_wall=time.perf_counter(),
        )
        try:
            self._sched.enqueue(req)
        except FriendlyError:
            self.metrics.record_reject()
            self.recorder.record(
                "rejected", tick=self.tick, prompt_len=int(prompt.size),
                reason="queue_full",
            )
            raise
        self._next_id += 1
        self.metrics.record_submit()
        span = self._tracer.span(
            "request", tick=self.tick, id=req.id,
            prompt_len=int(prompt.size), max_new_tokens=max_new_tokens,
        )
        span.event("queued", tick=self.tick, queue_depth=self.queue_depth)
        self._spans[req.id] = span
        return req.id

    def step(self) -> list[RequestResult]:
        """One scheduler tick: expire deadlines, admit queued requests
        into free slots (prefill per joiner), one fused decode step for
        all active slots, retire finished sequences. Returns the
        requests that reached a terminal state this tick."""
        t0 = time.perf_counter()
        tick = self._sched.tick_count
        finished = self._sched.expire(tick)

        with annotate("serve.admit"):
            while self._sched.queue_depth and self.pool.free_count:
                req = self._sched.pop_next()
                slot = self.pool.lease()
                span = self._spans.get(req.id)
                if span is not None:
                    span.event("admitted", tick=tick, slot=slot)
                with annotate("serve.prefill"):
                    p = len(req.prompt)
                    bucket = self.prefill_bucket(p)
                    padded = np.full((bucket,), self.pad_id, np.int32)
                    padded[:p] = req.prompt
                    tp = time.perf_counter()
                    first, cache = self._prefill(
                        self.variables, jnp.asarray(padded[None]), p - 1
                    )
                    # only the REAL prompt's K/V enter the slot; the pad
                    # tail of the bucket cache is dropped here
                    self.pool.write_prefill(slot, cache, p)
                    first = int(first[0])
                if span is not None:
                    span.event(
                        "prefill", tick=tick, bucket=bucket,
                        ms=round((time.perf_counter() - tp) * 1e3, 3),
                    )
                self.metrics.record_first_token(req, tick, bucket=bucket)
                done = self._sched.activate(slot, req, first, tick)
                if done is not None:
                    finished.append(done)

        if self._sched.active:
            n_active = len(self._sched.active)
            # live KV rows this step actually attends (pos + 1 per
            # active slot) vs the dense-over-cache_len read it replaced
            # — the decode FLOP-utilization figure in the metrics
            live_kv = sum(
                st.pos + 1 for st in self._sched.active.values()
            )
            tok, pos = self._sched.decode_inputs(self.pad_id)
            with annotate("serve.decode"):
                td = time.perf_counter()
                nxt, buffers = self._decode(
                    self.variables, self.pool.buffers,
                    jnp.asarray(tok), jnp.asarray(pos),
                )
                # the inputs were DONATED: rebind the pool to the step's
                # outputs before anything can touch the stale references
                self.pool.buffers = buffers
                nxt = np.asarray(nxt)  # host sync: (S,) int32 only
                decode_s = time.perf_counter() - td
                self.metrics.record_decode(
                    n_active, decode_s,
                    live_kv=live_kv, cache_len=self.cache_len,
                )
            decode_ms = round(decode_s * 1e3, 3)
            for st in self._sched.active.values():
                span = self._spans.get(st.req.id)
                if span is not None:
                    span.event("decode", tick=tick, pos=st.pos,
                               n_active=n_active, step_ms=decode_ms)
            finished.extend(self._sched.consume(nxt, tick))

        self._sched.tick_count += 1
        self.metrics.sample_tick(
            self._sched.queue_depth, self.pool.leased_count,
            time.perf_counter() - t0,
        )
        for res in finished:
            self.metrics.record_finish(res)
            span = self._spans.pop(res.id, None)
            if span is not None:
                span.end(res.status, tick=res.finish_tick,
                         generated=res.generated)
        return finished

    def run(self, max_ticks: int = 100_000) -> dict[int, RequestResult]:
        """Step until queue and slots drain; results keyed by request
        id. ``max_ticks`` bounds runaway loops (a generator that never
        emits EOS still retires at its token budget, so hitting the
        bound means a caller bug — reported as the typed error)."""
        results: dict[int, RequestResult] = {}
        start = self.tick
        # black-box contract: the flight recorder dumps its last N
        # events to the error log automatically when the typed error
        # escapes — the post-mortem for "what was the engine doing"
        with self.recorder.dump_on_friendly_error():
            while self._sched.busy:
                if self.tick - start >= max_ticks:
                    raise FriendlyError(
                        f"serve run() exceeded max_ticks ({max_ticks}) "
                        f"with {self._sched.queue_depth} queued and "
                        f"{len(self._sched.active)} active requests"
                    )
                for res in self.step():
                    results[res.id] = res
        return results
