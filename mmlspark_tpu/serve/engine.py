"""``ServeEngine`` — the public continuous-batching serving API.

Turns the repo's static-shape KV-cache decode (``models/generate.py``)
into a multi-tenant engine: requests of different prompt lengths and
arrival times share ONE jitted decode program over the slot pool's
fixed-shape buffers. The decode program is a FUSED BLOCK
(``models.generate.make_decode_block``): ``lax.scan`` over up to
``decode_block`` greedy micro-steps inside one dispatch, sampling and
advancing per-slot positions on device, with an on-device live/EOS/
budget mask so finished slots emit pads without branching — ONE host
sync per block instead of one per token, which is what the per-token
latency of a dispatch-bound small-model tick is made of. Block sizes
are clamped to a power-of-two ladder, so at most
``num_decode_blocks`` = O(log decode_block) decode programs ever
compile (asserted by ``tests/test_serve.py`` via
``decode_compile_count``; the ladder shrinks near per-request budgets
to keep token-for-token parity with ``generate()``). Prefill is its own
jitted program, BUCKETED by prompt length: prompts right-pad to
power-of-two buckets, so at most O(log cache_len) prefill programs ever
compile (``prefill_compile_count`` <= ``num_prefill_buckets``) —
joiners pay a bucketed prefill, the steady-state decode tick never
recompiles. The block reads each slot's cache through the length-aware
split-KV kernel (``ops/flash_attention.flash_decode``, with dead rows'
live lengths zeroed mid-block) and DONATES the pool's buffer pytree
plus the device positions/live mask, so all decode state updates in
place on device (docs/SERVING.md has the donation contract).

Usage::

    engine = ServeEngine(graph, variables, slots=8)
    rid = engine.submit(prompt_ids, max_new_tokens=32)   # queued
    results = engine.run()                                # drain
    results[rid].tokens                                   # prompt + gen

``submit`` is admission-controlled (bounded queue raises the typed
:class:`FriendlyError` when full) and validates per-request budgets
against the pool's ``cache_len``; ``step()`` runs one scheduler tick
(admit -> fused decode -> retire) and returns the requests that finished
on it; ``run()`` loops ``step()`` until idle. Decode is greedy
(temperature-0) — identical tokens to ``generate()`` per request, which
is the engine's correctness contract.
"""

from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from mmlspark_tpu.core import integrity
from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.integrity import SnapshotCorruption
from mmlspark_tpu.core.faults import (
    EngineKilled,
    FaultInjector,
    is_resource_exhausted,
    is_transient,
)
from mmlspark_tpu.core.perf import (
    SloMonitor,
    SloTargets,
    analyze_jit_cost,
    parse_slo_spec,
)
from mmlspark_tpu.core.telemetry import (
    FlightRecorder,
    RetraceWatchdog,
    SpanTracer,
)
from mmlspark_tpu.models.generate import (
    _cached_apply,
    greedy_next,
    init_cache,
    make_decode_block,
)
from mmlspark_tpu.parallel.mesh import make_mesh, parse_mesh_axes
from mmlspark_tpu.parallel.sharding import (
    TRANSFORMER_TP_RULES,
    shard_params,
)
from mmlspark_tpu.serve.cache_pool import SlotCachePool
from mmlspark_tpu.serve.metrics import ServeMetrics
from mmlspark_tpu.serve.scheduler import (
    ContinuousBatchScheduler,
    RequestResult,
    ServeRequest,
)
from mmlspark_tpu.testing.compile_guard import (
    ProgramCountingJit,
    jit_cache_size,
)
from mmlspark_tpu.utils.profiling import annotate


def _resolve_mesh(mesh):
    """Engine ``mesh`` argument -> jax Mesh or None. Accepts a built
    Mesh, an axes mapping (``{"data": -1, "model": 2}``), or the CLI
    string spelling (``"data=4,model=2"``)."""
    if mesh is None:
        return None
    if isinstance(mesh, str):
        mesh = parse_mesh_axes(mesh)
    if isinstance(mesh, dict):
        return make_mesh(mesh)
    return mesh


class ServeEngine:
    def __init__(self, graph, variables, *, slots: int = 4,
                 cache_len: int | None = None, max_queue: int = 16,
                 pad_id: int = 0, decode_block: int = 32,
                 mesh=None,
                 recorder: FlightRecorder | None = None,
                 faults: FaultInjector | None = None,
                 retry_limit: int = 3,
                 retry_backoff_s: float = 0.02,
                 degrade_recover_ticks: int = 8,
                 slo=None,
                 paged: bool = False, page_size: int | None = None,
                 num_pages: int | None = None,
                 prefix_cache: bool = False,
                 replica: int | None = None,
                 snapshot_every_ticks: int | None = None,
                 kv_dtype: str = "bf16",
                 quantize_weights: bool = False,
                 role: str = "both",
                 prefill_chunk: int | None = None,
                 async_host: bool = False,
                 registry=None):
        if not graph.extra.get("causal", False):
            raise FriendlyError(
                f"serving needs a causal LM; '{graph.name}' has "
                "causal=False"
            )
        max_len = graph.input_shape[0] if graph.input_shape else None
        if cache_len is None:
            if not max_len:
                raise FriendlyError(
                    f"'{graph.name}' records no input_shape; pass "
                    "cache_len explicitly to size the slot KV buffers"
                )
            cache_len = max_len
        if (
            max_len
            and cache_len > max_len
            and graph.extra.get("pos_embedding", "learned") == "learned"
        ):
            raise FriendlyError(
                f"cache_len ({cache_len}) exceeds the learned position "
                f"table ({max_len}); build the model with a larger "
                "max_len or pos_embedding='rope'"
            )
        window = graph.extra.get("window")
        if window and window < cache_len:
            raise FriendlyError(
                f"'{graph.name}' uses a sliding window ({window}) "
                f"smaller than cache_len ({cache_len}); the slot pool "
                "holds linear per-slot buffers only — rolled circular "
                "buffers are not pooled yet. Serve with cache_len <= "
                "window, or build the model without window"
            )
        if decode_block < 1:
            raise FriendlyError(
                f"decode_block must be >= 1, got {decode_block} "
                "(1 = per-token dispatch, larger fuses T micro-steps "
                "into one device program)"
            )
        # chunked prefill (docs/SERVING.md "Chunked prefill"): cap the
        # widest prefill dispatch at ``prefill_chunk`` tokens — a long
        # prompt's fill becomes a sequence of bounded chunk dispatches
        # interleaved with decode ticks, so one joiner can never
        # head-of-line-block every co-resident stream. Chunk widths
        # live on the SAME power-of-two ladder as prefill buckets
        # ({8, 16, ..., prefill_chunk}), so the compile pin tightens to
        # ``prefill_compile_count <= num_chunk_buckets``.
        if prefill_chunk is not None:
            if (
                prefill_chunk < 8
                or prefill_chunk & (prefill_chunk - 1)
            ):
                raise FriendlyError(
                    f"prefill_chunk must be a power of two >= 8 (the "
                    f"prefill bucket ladder's floor), got {prefill_chunk}"
                )
            if prefill_chunk > cache_len:
                raise FriendlyError(
                    f"prefill_chunk ({prefill_chunk}) exceeds cache_len "
                    f"({cache_len}); a chunk wider than the KV buffers "
                    "can never be dispatched — drop the flag or shrink "
                    "the chunk"
                )
            if graph.extra.get("n_experts"):
                raise FriendlyError(
                    f"'{graph.name}' is a MoE model, which prefills at "
                    "exact length (expert-capacity routing is not "
                    "causal, so padded chunk windows could change real "
                    "tokens' expert assignment); chunked prefill "
                    "requires bucketed prefill — drop prefill_chunk"
                )
        self._prefill_chunk = prefill_chunk
        # pipelined async host loop (docs/SERVING.md "Async host
        # loop"): dispatch block N+1 behind block N's in-flight
        # execution and only then fetch N's tokens, so host work
        # (scheduling, SLO eval, telemetry, fault hooks) overlaps into
        # device time. Token streams stay bit-identical — pipelining
        # reorders HOST work, never device programs' inputs (see
        # _decode_phase_async for the identity-fence and deferred-free
        # machinery that guarantees it).
        self._async_host = bool(async_host)
        #: in-flight decode block record (async mode): set at dispatch,
        #: consumed by the NEXT tick's fetch
        self._inflight: dict | None = None
        #: monotone dispatch generation stamping the pools' deferred
        #: frees — a freed slot returns to the free list only after the
        #: block that saw it live has been fetched
        self._dispatch_gen = 0
        #: when the previously fetched block's outputs materialized —
        #: the queued-vs-executing attribution anchor for the next
        #: pipelined dispatch interval (core/perf.py record_dispatch)
        self._prev_block_done = 0.0
        self.graph = graph
        self.pad_id = pad_id
        self.cache_len = cache_len
        # floor to a power of two: block sizes live on the ladder
        # {1, 2, 4, ..., decode_block}, so the scan-length static arg
        # compiles O(log) program variants, never one per budget
        self.decode_block = 1 << (int(decode_block).bit_length() - 1)
        # sharded serving (docs/SERVING.md "Sharded serving"): with a
        # mesh, params commit to the model axis by the Megatron rules
        # and the pool's slot-batched state to the data axis; GSPMD
        # partitions the SAME prefill/decode programs — XLA inserts the
        # collectives, token streams stay bit-identical to the
        # single-device engine, and the compile-count pins hold because
        # every per-tick input is committed to a fixed NamedSharding
        self.mesh = _resolve_mesh(mesh)
        # weight-only int8 serving (docs/PERFORMANCE.md "Quantized
        # decode"): the device-resident weights are per-channel int8
        # (min_size=0 — at decode batch sizes EVERY matmul is
        # bandwidth-bound) and each jitted program dequantizes to bf16
        # INSIDE jit, so XLA fuses the convert into the consuming
        # matmul and HBM streams half the bytes per forward. Under a
        # mesh the quantized pytree is REPLICATED: its {int8, scale}
        # dict leaves are outside the Megatron path rules, so the
        # weight-HBM win trades away tensor-parallel weight sharding
        # (docs/SERVING.md records the trade).
        self._quantized_weights = bool(quantize_weights)
        if quantize_weights:
            from mmlspark_tpu.ops.quantize import (
                quantize_weights as _quantize_variables,
            )

            qvars = _quantize_variables(variables, min_size=0)
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                qvars = jax.device_put(
                    qvars, NamedSharding(self.mesh, PartitionSpec())
                )
            self.variables = qvars
        else:
            self.variables = (
                shard_params(variables, self.mesh, TRANSFORMER_TP_RULES)
                if self.mesh is not None else variables
            )
        # every jitted program below dequantizes through this hook; the
        # identity on unquantized engines keeps traces byte-identical
        # to previous builds
        if quantize_weights:
            from mmlspark_tpu.ops.quantize import dequantize_weights
            _deq = dequantize_weights
        else:
            def _deq(v):
                return v
        # paged KV cache (docs/SERVING.md "Paged KV cache"): the
        # PagedCachePool virtualizes slot memory behind fixed-shape page
        # stores + per-slot page tables — same compiled programs, same
        # donation/sharding/compile-pin contracts, but HBM scales with
        # pages actually mapped and shared prompt prefixes prefill once
        if not paged and (
            page_size is not None or num_pages is not None or prefix_cache
        ):
            raise FriendlyError(
                "page_size/num_pages/prefix_cache configure the paged "
                "KV cache; pass paged=True to enable it"
            )
        self._paged = bool(paged)
        self._prefix_cache = bool(paged and prefix_cache)
        self.kv_dtype = kv_dtype
        if paged:
            from mmlspark_tpu.serve.paging import PagedCachePool

            self.pool = PagedCachePool(
                graph, variables, slots, cache_len, mesh=self.mesh,
                page_size=page_size, num_pages=num_pages,
                prefix_cache=prefix_cache, kv_dtype=kv_dtype,
            )
        else:
            self.pool = SlotCachePool(graph, variables, slots, cache_len,
                                      mesh=self.mesh, kv_dtype=kv_dtype)
        # replica identity (serve/supervisor.py): tags every fault-hook
        # firing (so replica-pinned kills target THIS engine) and
        # namespaces the registry metric names per replica
        if replica is not None and replica < 0:
            raise FriendlyError(
                f"replica index must be >= 0, got {replica}"
            )
        self._replica = replica
        # disaggregated-fleet role (docs/SERVING.md "Disaggregated
        # fleet"): "prefill" engines run admission + prefill only and
        # retire each request as "handed_off" with its KV payload in
        # the outbox; "decode" engines adopt those payloads by direct
        # KV write (and keep FULL prefill capability — the fallback
        # when a hand-off is lost keeps streams bit-identical);
        # "both" (the default) is the classic homogeneous engine.
        if role not in ("both", "prefill", "decode"):
            raise FriendlyError(
                f"role must be 'both', 'prefill' or 'decode', got "
                f"{role!r}"
            )
        self.role = role
        #: KV hand-off payloads awaiting collection by the fleet
        #: (prefill-role engines fill this; ``take_handoffs`` drains)
        self._outbox: list[dict] = []
        #: engine-local request id -> pending hand-off payload, popped
        #: by the admit loop for the direct-KV-write adoption path
        self._handoffs: dict[int, dict] = {}
        # periodic snapshot cadence: every N ticks, step() refreshes
        # ``last_snapshot`` through the serve.snapshot fault hook — the
        # supervisor's recovery point. None (the default) keeps
        # snapshotting fully caller-driven, zero work per tick.
        if snapshot_every_ticks is not None and snapshot_every_ticks < 1:
            raise FriendlyError(
                f"snapshot_every_ticks must be >= 1, got "
                f"{snapshot_every_ticks}"
            )
        self._snapshot_every = snapshot_every_ticks
        self._last_snapshot: dict | None = None
        #: set when an EngineKilled escaped and the device resources
        #: were parked — the engine refuses further steps (restore
        #: from a snapshot instead)
        self._dead = False
        # ``registry``: hand the metrics plane a shared (usually
        # namespaced — core/telemetry.NamespacedRegistry) registry so
        # several engines' expositions merge collision-free; None (the
        # default) keeps the engine's registry private as before
        self.metrics = ServeMetrics(
            graph.name, slots, registry=registry,
            decode_block=self.decode_block,
            mesh_shape=(
                {k: int(v) for k, v in self.mesh.shape.items()}
                if self.mesh is not None else {}
            ),
            mesh_devices=(
                int(self.mesh.size) if self.mesh is not None else 1
            ),
            cache_pool_bytes_per_device=(
                self.pool.device_bytes_per_device()
            ),
            kv_dtype=kv_dtype,
            prefill_chunk=prefill_chunk or 0,
            async_host=self._async_host,
            namespace=(
                f"replica{replica}." if replica is not None else ""
            ),
        )
        if paged:
            self.metrics.attach_paging(self.pool.paging_stats)
        #: flight recorder (core/telemetry): one span per request
        #: lifecycle — queued -> admitted -> prefill[bucket] -> decode
        #: ticks -> finished/expired — dumpable as events.jsonl via the
        #: CLI's ``--telemetry-dir`` (docs/OBSERVABILITY.md)
        self.recorder = recorder if recorder is not None else FlightRecorder()
        self._tracer = SpanTracer(self.recorder)
        self._spans: dict[int, object] = {}
        self._sched = ContinuousBatchScheduler(self.pool,
                                               max_queue=max_queue)
        self._next_id = 0

        # resilience layer (docs/SERVING.md "Failure semantics"):
        # transient dispatch errors retry behind capped deterministic
        # backoff; RESOURCE_EXHAUSTED steps down the decode-block
        # ladder and caps admissions (graceful degradation — NO new XLA
        # programs, the ladder sizes already exist); a request that
        # still cannot make progress is QUARANTINED (terminal status
        # "failed", slot freed, device live mask forced dead) instead
        # of killing run(). ``faults`` is the deterministic injection
        # harness (core/faults.py); None (the default) keeps every hook
        # a single attribute check — zero work on the hot path.
        if retry_limit < 0:
            raise FriendlyError(
                f"retry_limit must be >= 0, got {retry_limit}"
            )
        self._faults = faults
        self._retry_limit = retry_limit
        self._retry_backoff_s = retry_backoff_s
        self._degrade_recover_ticks = max(1, degrade_recover_ticks)
        #: memory-pressure degradation state: the current decode-block
        #: ceiling (walks DOWN the existing power-of-two ladder on OOM,
        #: re-escalates after ``degrade_recover_ticks`` clean ticks)
        #: and the concurrent-admission cap
        self._block_cap = self.decode_block
        self._admit_cap = slots
        self._ok_ticks = 0
        #: vocab for token-stream validation (poison detection); None
        #: when the builder records no vocab — validation then only
        #: rejects negatives
        self._vocab = graph.extra.get("vocab_size")
        # SLO plane (docs/OBSERVABILITY.md "Declaring SLOs"): ``slo``
        # accepts the CLI string spelling, SloTargets, or a prebuilt
        # SloMonitor. When targets burn, the monitor's shed signal
        # suppresses NEW admissions (in-flight requests finish) — load
        # shedding composes with memory-pressure degradation: both
        # squeeze the admit loop, neither touches compiled programs.
        if isinstance(slo, str):
            slo = parse_slo_spec(slo)
        if isinstance(slo, SloTargets):
            slo = SloMonitor(slo, recorder=self.recorder,
                             registry=self.metrics.registry)
        self._slo: SloMonitor | None = slo
        if slo is not None:
            self.metrics.attach_slo(slo)
        if self._faults is not None and self._faults.listener is None:
            # injected faults land in the same metrics + event timeline
            # as their consequences (retries, quarantines, degradation)
            def _on_fault(kind: str, site: str) -> None:
                self.metrics.record_fault(kind)
                self.recorder.record(
                    "fault_injected", tick=self.tick, kind=kind,
                    site=site,
                )
            self._faults.listener = _on_fault

        # bucketed prefill: prompts are right-padded to power-of-two
        # length buckets, so the prefill program count is O(log
        # cache_len) instead of O(distinct prompt lengths). Causality
        # makes the pads invisible: pad positions sit AFTER every real
        # token, the real positions' K/V and logits cannot see them, and
        # ``last`` (traced, so no retrace per value) slices the true
        # last-token logits out of the padded row. MoE models opt out —
        # their expert-capacity routing is not causal (a pad consumes
        # capacity that can change a REAL token's expert), so they keep
        # exact-length prefill.
        self._bucketed = not graph.extra.get("n_experts")

        def _prefill(variables, prompt, last):
            # (1, B) padded prompt -> first greedy token (from position
            # ``last``, the true prompt end) + a length-B linear cache;
            # jit retraces per distinct BUCKET
            cache = init_cache(graph, variables, 1, prompt.shape[1])
            variables = _deq(variables)
            logits, cache = _cached_apply(graph, variables, prompt,
                                          cache, 0)
            cur = jax.lax.dynamic_slice_in_dim(
                logits, last, 1, axis=1
            )[:, 0]
            return greedy_next(cur), cache

        # both programs run behind the retrace watchdog: any compile
        # beyond the design's budget (decode: one per ladder block
        # size, prefill: one per bucket) is logged the moment it
        # happens with the abstract shapes that triggered it, and lands
        # in the flight recorder's event timeline next to the request
        # that caused it
        # ProgramCountingJit makes the counts true XLA-program counts
        # even under a mesh, where jax's raw signature cache would
        # re-register NamedSharding-committed args as "new shapes"
        # (testing/compile_guard.py) — the pins and watchdog budgets
        # therefore hold unchanged on sharded engines
        self._prefill = RetraceWatchdog(
            ProgramCountingJit(jax.jit(_prefill)), "serve.prefill",
            registry=self.metrics.registry, recorder=self.recorder,
            expected_programs=self.num_prefill_buckets,
        )

        # prefix-cache RESUME prefill (docs/SERVING.md "Paged KV
        # cache"): a prompt sharing a cached prefix runs the forward
        # over the REMAINDER only, against the prefix's gathered linear
        # K/V. ``pos``/``last`` are traced, so programs are keyed by the
        # remainder BUCKET alone — the same O(log cache_len) ceiling as
        # full prefill.
        def _resume(variables, ids, cache, pos, last):
            logits, cache = _cached_apply(graph, _deq(variables), ids,
                                          cache, pos)
            cur = jax.lax.dynamic_slice_in_dim(
                logits, last, 1, axis=1
            )[:, 0]
            return greedy_next(cur), cache

        self._resume = None
        if self._prefix_cache:
            self._resume = RetraceWatchdog(
                ProgramCountingJit(jax.jit(_resume)), "serve.resume",
                registry=self.metrics.registry, recorder=self.recorder,
                expected_programs=self.num_prefill_buckets,
            )

        # the chunked-fill program IS the resume body: one forward over
        # a chunk window of the sequence against the fill's carry cache
        # (a full-cache_len linear cache), keyed by the chunk BUCKET
        # alone — ``pos``/``last`` are traced and the carry's shape is
        # fixed, so at most ``num_chunk_buckets`` programs ever compile
        # unlike resume (one shot, output handed straight to
        # write_prefill), the chunk program's output cache RE-ENTERS the
        # next chunk call as the carry — under a mesh the outputs are
        # pinned replicated so the signature reaches its fixed point on
        # the first call instead of retracing on GSPMD's own choice
        chunk_kwargs = {}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            chunk_kwargs["out_shardings"] = NamedSharding(
                self.mesh, PartitionSpec()
            )
        self._chunk = None
        if self._prefill_chunk is not None:
            self._chunk = RetraceWatchdog(
                ProgramCountingJit(jax.jit(_resume, **chunk_kwargs)),
                "serve.chunk",
                registry=self.metrics.registry, recorder=self.recorder,
                expected_programs=self.num_chunk_buckets,
            )
        # the FUSED decode block (models.generate.make_decode_block):
        # lax.scan over t greedy micro-steps with the scan length
        # static (one program per ladder size) and the whole device
        # decode state DONATED — the slot-pool cache pytree AND the
        # per-slot positions/live mask update in place on device.
        # Contract: the engine immediately rebinds pool.buffers/
        # positions/live to the block's outputs and nothing else may
        # hold the donated references (docs/SERVING.md).
        # under a mesh the block's loop-carried outputs are PINNED to
        # the pool's canonical shardings (out_shardings): tick N's
        # outputs re-enter tick N+1 with byte-identical placement, so
        # the signature reaches its fixed point on the first call and
        # the ladder pins hold — GSPMD would otherwise pick output
        # shardings of its own and every tick would re-register
        jit_kwargs = {}
        if self.mesh is not None:
            slot_sh = self.pool.slot_sharding
            jit_kwargs["out_shardings"] = (
                slot_sh, slot_sh, self.pool.kv_shardings, slot_sh,
            )
        _raw_block = make_decode_block(graph, pad_id)
        if self._quantized_weights:
            # dequantize INSIDE the jitted block (same signature, same
            # static/donate argnums — the jit contract is untouched);
            # the int8 weights convert once per dispatch and XLA fuses
            # the convert into each consuming matmul
            def _block(variables, buffers, pos, live, tok, rem, eos, t):
                return _raw_block(_deq(variables), buffers, pos, live,
                                  tok, rem, eos, t)
        else:
            _block = _raw_block
        self._decode = RetraceWatchdog(
            ProgramCountingJit(jax.jit(
                _block,
                static_argnums=(7,), donate_argnums=(1, 2, 3),
                **jit_kwargs,
            )),
            "serve.decode",
            registry=self.metrics.registry, recorder=self.recorder,
            expected_programs=self.num_decode_blocks,
        )

    # -- prefill buckets ---------------------------------------------------

    def prefill_bucket(self, prompt_len: int) -> int:
        """Padded length the prefill program runs at for a prompt of
        ``prompt_len``: the next power of two >= max(prompt_len, 8),
        capped at ``cache_len`` (admission control guarantees
        prompt_len < cache_len, so the cap always covers the prompt).
        MoE engines bucket at exact length (see ``__init__``)."""
        if not self._bucketed:
            return prompt_len
        bucket = 8
        while bucket < prompt_len:
            bucket *= 2
        return min(bucket, self.cache_len)

    def chunk_bucket(self, n: int) -> int:
        """Padded width the chunked-fill program runs at for a chunk of
        ``n`` real tokens: the next power of two >= max(n, 8), capped at
        ``prefill_chunk``. Intermediate chunks are exactly
        ``prefill_chunk`` wide (the top bucket); only a fill's FINAL
        chunk can land on a smaller rung."""
        bucket = 8
        while bucket < n:
            bucket *= 2
        return min(bucket, self._prefill_chunk)

    @property
    def num_chunk_buckets(self) -> int:
        """How many distinct chunked-fill programs CAN exist — one per
        ladder width in {8, 16, ..., prefill_chunk}; 0 with chunking
        off."""
        if self._prefill_chunk is None:
            return 0
        return self._prefill_chunk.bit_length() - 3

    @property
    def num_prefill_buckets(self) -> int:
        """How many distinct prefill programs CAN exist for this engine
        — the ceiling the compile-guard tests pin prefill to. With
        chunked prefill the monolithic program never runs and the
        ceiling is the CHUNK ladder's (``num_chunk_buckets`` <= the
        monolithic count, since the chunk cap truncates the bucket
        ladder)."""
        if self._prefill_chunk is not None:
            return self.num_chunk_buckets
        return len({
            self.prefill_bucket(p) for p in range(1, self.cache_len)
        })

    # -- decode-block ladder ----------------------------------------------

    def _block_size(self, min_rem: int) -> int:
        """This tick's fused-block scan length: the largest ladder power
        of two <= min(decode_block, minimum remaining budget over active
        slots). Clamping to the min budget is the "shrink near budgets"
        parity rule: no slot can overrun its budget mid-block, so budget
        exhaustion only ever lands exactly on a block boundary (the only
        mid-block death is EOS, which the on-device mask handles).
        Under memory-pressure degradation the ceiling is ``_block_cap``
        (<= decode_block) — still on the ladder, so no new programs."""
        cap = min(self._block_cap, max(1, min_rem))
        t = 1
        while t * 2 <= cap:
            t *= 2
        return t

    @property
    def num_decode_blocks(self) -> int:
        """How many distinct fused decode-block programs CAN exist for
        this engine — one per ladder size T in {1, 2, 4, ...,
        decode_block}, the ceiling the compile-guard tests pin decode
        to. Scan iterations inside a block share one program; only
        distinct static scan lengths compile separately."""
        return self.decode_block.bit_length()

    # -- fault handling ----------------------------------------------------

    @property
    def degraded(self) -> bool:
        """True while memory-pressure degradation holds the engine
        below full service (reduced block ladder ceiling or admission
        cap); the recovery probe clears it."""
        return (
            self._block_cap < self.decode_block
            or self._admit_cap < self.pool.num_slots
        )

    def _backoff(self, attempts: int) -> None:
        """Capped DETERMINISTIC backoff before a retry: linear in the
        attempt number, no jitter — reproducibility is worth more to
        this in-process engine than thundering-herd protection."""
        self.metrics.record_retry()
        self.recorder.record("retry", tick=self.tick, attempt=attempts)
        if self._retry_backoff_s > 0:
            time.sleep(self._retry_backoff_s * attempts)

    def _note_oom(self, tick: int, site: str) -> None:
        """Graceful degradation on RESOURCE_EXHAUSTED: step DOWN the
        existing power-of-two decode-block ladder (never a new XLA
        program) and tighten the admission cap; at the ladder floor,
        preempt the youngest active request — its emitted tokens fold
        into a resume prefix and it re-queues, so memory pressure costs
        latency, not data. A recovery probe re-escalates after
        ``degrade_recover_ticks`` clean ticks."""
        if self._block_cap > 1:
            self._block_cap //= 2
        elif len(self._sched.active) > 1:
            # youngest active slot: the most recently admitted request
            # has the least sunk decode work to re-prefill on resume
            slot = next(reversed(self._sched.active))
            req = self._sched.preempt(slot)
            self._sched.requeue(req)
            self.metrics.record_preemption()
            span = self._spans.get(req.id)
            if span is not None:
                span.event("preempted", tick=tick, slot=slot,
                           prefix_len=len(req.prefix))
            self.recorder.record(
                "preempted", tick=tick, id=req.id, slot=slot,
                prefix_len=len(req.prefix),
            )
        self._admit_cap = max(1, self._admit_cap - 1)
        self._ok_ticks = 0
        self.metrics.set_degraded(True)
        self.recorder.record(
            "degraded", tick=tick, site=site,
            block_cap=self._block_cap, admit_cap=self._admit_cap,
        )

    def _note_clean_dispatch(self, tick: int) -> None:
        """Recovery probe: after ``degrade_recover_ticks`` consecutive
        clean decode dispatches, re-escalate one notch (block ladder
        up one power of two, admission cap up one slot) — degradation
        is a pressure response, not a ratchet."""
        if not self.degraded:
            return
        self._ok_ticks += 1
        if self._ok_ticks < self._degrade_recover_ticks:
            return
        self._ok_ticks = 0
        self._block_cap = min(self.decode_block, self._block_cap * 2)
        self._admit_cap = min(self.pool.num_slots, self._admit_cap + 1)
        self.metrics.set_degraded(self.degraded)
        self.recorder.record(
            "recovered" if not self.degraded else "re_escalated",
            tick=tick, block_cap=self._block_cap,
            admit_cap=self._admit_cap,
        )

    def _token_ok(self, token: int) -> bool:
        """Token-stream sanity: device-sampled greedy tokens are argmax
        indices, so they are non-negative and < vocab — anything else
        is corruption (e.g. an injected poison) and quarantines the
        request before it can reach results or the KV frontier."""
        if token < 0:
            return False
        return self._vocab is None or token < int(self._vocab)

    def _quarantine_slot(self, slot: int, tick: int,
                         reason: str) -> RequestResult:
        """Retire one ACTIVE request as ``"failed"``: the slot frees
        (device live mask forced dead, position zeroed — the row emits
        pads and reads no KV until re-leased) and the engine keeps
        serving everyone else."""
        res = self._sched.fail(slot, tick)
        self.metrics.record_quarantine()
        span = self._spans.get(res.id)
        if span is not None:
            span.event("quarantined", tick=tick, slot=slot,
                       reason=reason)
        self.recorder.record(
            "quarantine", tick=tick, id=res.id, slot=slot, reason=reason,
        )
        return res

    def _quarantine_unactivated(self, req, slot: int, tick: int,
                                reason: str) -> RequestResult:
        """Retire a request whose prefill never succeeded (lease still
        held by the admit loop) as ``"failed"``."""
        self.pool.free(slot)
        res = self._sched.fail_unactivated(req, tick)
        self.metrics.record_quarantine()
        span = self._spans.get(req.id)
        if span is not None:
            span.event("quarantined", tick=tick, slot=slot,
                       reason=reason)
        self.recorder.record(
            "quarantine", tick=tick, id=req.id, slot=slot, reason=reason,
        )
        return res

    # -- introspection -----------------------------------------------------

    @property
    def tick(self) -> int:
        return self._sched.tick_count

    @property
    def queue_depth(self) -> int:
        return self._sched.queue_depth

    @property
    def busy(self) -> bool:
        return self._sched.busy

    @property
    def decode_compile_count(self) -> int:
        """How many DISTINCT XLA programs the fused decode block has
        compiled — one per ladder size actually run, never more than
        ``num_decode_blocks`` for the life of the engine (asserted in
        tests; the retrace watchdog logs any violation live with the
        triggering shapes). Scan iterations do NOT count: a T=32 block
        is one program, not 32."""
        return jit_cache_size(self._decode)

    @property
    def prefill_compile_count(self) -> int:
        """How many prefill programs have compiled — bounded by
        ``num_prefill_buckets`` for the life of the engine (asserted in
        tests), however many distinct prompt lengths arrive. With
        chunked prefill every fill runs through the chunk program, so
        the count (and its ``num_chunk_buckets`` ceiling) is the chunk
        ladder's."""
        if self._prefill_chunk is not None:
            return jit_cache_size(self._chunk)
        return jit_cache_size(self._prefill)

    @property
    def resume_compile_count(self) -> int:
        """How many prefix-resume programs have compiled — keyed by the
        REMAINDER bucket, so bounded by ``num_prefill_buckets`` like
        full prefill; 0 without the prefix cache."""
        if self._resume is None:
            return 0
        return jit_cache_size(self._resume)

    # -- public API --------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: int | None = None,
               deadline_ticks: int | None = None,
               trace_id: str | None = None) -> int:
        """Queue one request; returns its id. Raises
        :class:`FriendlyError` on invalid budgets or a full queue
        (admission control) — never a bare KeyError/ValueError.

        ``deadline_ticks``: the request must FINISH within that many
        scheduler ticks of submission or it expires (queued or
        mid-decode), surfacing as status ``"expired"``.

        ``trace_id``: fleet-wide trace-context id stamped on the
        request's span and every hand-off payload derived from it
        (docs/OBSERVABILITY.md "Distributed tracing"); supervisors
        pass their global id here so one request's fragments across
        replicas stay joinable. Default: the engine mints
        ``t{request_id}``.
        """
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise FriendlyError(
                f"prompt must be a non-empty 1-D token vector, got "
                f"shape {prompt.shape} (the engine serves one request "
                "per submit; batch by submitting several)"
            )
        if max_new_tokens < 1:
            raise FriendlyError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if int(prompt.size) >= self.cache_len:
            # pointed admission error BEFORE the generic budget check:
            # a prompt this long can never fit a single generated token
            # in the slot buffers, whatever the budget
            raise FriendlyError(
                f"prompt length ({prompt.size}) must be < the engine's "
                f"cache_len ({self.cache_len}); truncate the prompt or "
                "build the engine with a larger cache_len"
            )
        if self._vocab is not None and prompt.size:
            lo, hi = int(prompt.min()), int(prompt.max())
            if lo < 0 or hi >= int(self._vocab):
                raise FriendlyError(
                    f"prompt tokens must be in [0, {self._vocab}) for "
                    f"'{self.graph.name}', got range [{lo}, {hi}]"
                )
        total = int(prompt.size) + max_new_tokens
        if total > self.cache_len:
            raise FriendlyError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's cache_len "
                f"({self.cache_len}); shorten the request or build the "
                "engine with a larger cache_len"
            )
        if deadline_ticks is not None and deadline_ticks < 1:
            raise FriendlyError(
                f"deadline_ticks must be >= 1, got {deadline_ticks}"
            )
        req = ServeRequest(
            id=self._next_id,
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_id=eos_id,
            deadline_tick=(
                self.tick + deadline_ticks
                if deadline_ticks is not None else None
            ),
            submit_tick=self.tick,
            submit_wall=time.perf_counter(),
            trace_id=trace_id or f"t{self._next_id}",
        )
        try:
            self._sched.enqueue(req)
        except FriendlyError:
            self.metrics.record_reject()
            self.recorder.record(
                "rejected", tick=self.tick, prompt_len=int(prompt.size),
                reason="queue_full",
            )
            raise
        self._next_id += 1
        self.metrics.record_submit()
        span = self._tracer.span(
            "request", tick=self.tick, id=req.id, trace=req.trace_id,
            prompt_len=int(prompt.size), max_new_tokens=max_new_tokens,
        )
        span.event("queued", tick=self.tick, queue_depth=self.queue_depth)
        self._spans[req.id] = span
        return req.id

    def step(self) -> list[RequestResult]:
        """One scheduler tick: expire deadlines, admit queued requests
        into free slots (prefill per joiner), ONE fused decode block of
        up to ``decode_block`` tokens for all active slots, retire
        finished sequences. Admission and retirement happen at block
        boundaries; the single host sync per tick fetches the whole
        ``(S, T)`` token block plus the finished vector. Returns the
        requests that reached a terminal state this tick.

        An :class:`EngineKilled` escaping the tick (the simulated
        process crash) first PARKS the device resources
        deterministically — every leased slot returns to the pool, a
        paged pool's page mappings release — so a supervisor that
        restores this engine's snapshot in the same process never
        double-holds pages; the dead engine then refuses further
        steps."""
        if self._dead:
            raise FriendlyError(
                "this engine was killed (EngineKilled) and its device "
                "resources parked; rebuild it with "
                "ServeEngine.restore(snapshot, ...) instead of "
                "stepping it again"
            )
        try:
            return self._step_inner()
        except EngineKilled:
            self._park_after_kill()
            raise

    def _step_inner(self) -> list[RequestResult]:
        t0 = time.perf_counter()
        tick = self._sched.tick_count
        finished = self._sched.expire(tick)
        tokens_this_tick = 0

        # SLO load shedding: while the monitor's budget burns, NEW
        # admissions stop (in-flight requests keep decoding, so the
        # overload actually drains). An IDLE engine admits regardless —
        # with nothing in flight, shedding could never observe recovery
        # and would deadlock the queue.
        shedding = (
            self._slo is not None and self._slo.should_shed
            and self.pool.leased_count > 0
        )
        if shedding and self._sched.queue_depth:
            self.metrics.record_slo_shed()
            self.recorder.record(
                "slo_shed", tick=tick,
                queue_depth=self._sched.queue_depth,
            )

        with annotate("serve.admit"):
            while (
                not shedding
                and self._sched.queue_depth
                and self.pool.free_count
                # admission cap: memory-pressure degradation admits
                # fewer concurrent requests than the pool has slots
                and self.pool.leased_count < self._admit_cap
            ):
                req = self._sched.pop_next()
                slot = self.pool.lease()
                span = self._spans.get(req.id)
                if span is not None:
                    span.event("admitted", tick=tick, slot=slot)
                # preempted/restored requests re-prefill prompt + the
                # tokens already emitted: greedy determinism makes the
                # resumed stream bit-identical to an uninterrupted one
                seq = (
                    np.concatenate([req.prompt, req.prefix])
                    if len(req.prefix) else req.prompt
                )
                first = None
                attempts = 0
                # cross-replica KV hand-off adoption (serve/fleet.py):
                # the payload's cache is another replica's prefill
                # program output for this EXACT sequence, so a direct
                # write into the leased slot is bit-identical to
                # running prefill here — no forward pass, no XLA
                # program. The write travels the ``serve.handoff``
                # fault hook; a payload that cannot land falls back to
                # the full local prefill below (greedy determinism
                # keeps the resulting stream bit-identical).
                payload = self._handoffs.pop(req.id, None)
                adopted = False
                if payload is not None and self._faults is not None:
                    # the serve.handoff silent-corruption drill: a
                    # seeded bit-flip in one KV leaf between production
                    # and adoption
                    cseed = self._faults.corrupt_spec(
                        "serve.handoff", tick=tick, request=req.id,
                        replica=self._replica,
                    )
                    if cseed is not None:
                        payload = integrity.corrupt_payload(payload,
                                                            cseed)
                if payload is not None:
                    ok, expected, actual = integrity.verify_payload(
                        payload
                    )
                    if not ok:
                        # checksum mismatch: the payload is untrusted —
                        # discard it and rebuild the same KV from the
                        # prompt via the full-prefill path below
                        # (greedy determinism keeps the stream
                        # bit-identical)
                        self.metrics.record_integrity_handoff_failure()
                        self.recorder.record(
                            "integrity.handoff_checksum", tick=tick,
                            id=req.id, expected=expected, actual=actual,
                        )
                        self.metrics.record_handoff_fallback()
                        self.recorder.record(
                            "handoff_fallback", tick=tick, id=req.id,
                        )
                        payload = None
                if payload is not None:
                    with annotate("serve.handoff"):
                        p = len(seq)
                        bucket = self.prefill_bucket(p)
                        cache = payload["kv"]
                        tp = time.perf_counter()
                        while True:
                            try:
                                if self._faults is not None:
                                    self._faults.fire(
                                        "serve.handoff", tick=tick,
                                        request=req.id,
                                        replica=self._replica,
                                    )
                                self.pool.write_prefill(slot, cache, p)
                                if self._prefix_cache:
                                    self.pool.prefix_insert(slot, seq)
                                first = int(payload["first_token"])
                                adopted = True
                                break
                            except Exception as e:
                                if is_resource_exhausted(e):
                                    self._note_oom(tick,
                                                   "serve.handoff")
                                elif not is_transient(e):
                                    raise
                                attempts += 1
                                if attempts > self._retry_limit:
                                    break
                                self._backoff(attempts)
                    if not adopted:
                        # lost/undeliverable hand-off: the request
                        # stays, the payload is discarded, and the
                        # full-prefill path below rebuilds the same
                        # KV from the prompt (attempts carry over
                        # into its retry budget)
                        self.metrics.record_handoff_fallback()
                        self.recorder.record(
                            "handoff_fallback", tick=tick, id=req.id,
                        )
                if not adopted and self._prefill_chunk is not None:
                    # chunked prefill: admission only STARTS the fill
                    # (prefix probe + carry allocation — no forward
                    # pass); _advance_fills below dispatches bounded
                    # chunk windows, one per tick per fill, so a long
                    # prompt can never monopolize a tick. A fill no
                    # wider than one chunk still completes on its
                    # admission tick — short-prompt TTFT is unchanged.
                    self._start_fill(req, slot, seq, tick)
                    continue
                # prefix-cache probe: a hit swaps the full-prompt
                # prefill for a REMAINDER resume against the cached
                # prefix's pages (shared, refcounted — the prefix
                # prefilled once, ever)
                hit = (
                    self.pool.prefix_lookup(
                        seq, self.prefill_bucket, slot=slot
                    )
                    if self._prefix_cache and not adopted else None
                )
                keep = 0
                with annotate("serve.prefill"):
                    p = len(seq)
                    if hit is not None:
                        entry, keep = hit
                        r = p - keep
                        bucket = self.prefill_bucket(r)
                        padded = np.full((bucket,), self.pad_id,
                                         np.int32)
                        padded[:r] = seq[keep:]
                        # the resume input: the prefix's K/V gathered
                        # back into a linear cache (an eager page read,
                        # no donation — retries reuse it)
                        lin = self.pool.gather_prefix(entry, keep)
                        family = f"resume[{bucket}]"
                        if self.metrics.perf.wants_program(family):
                            self.metrics.perf.register_program(
                                family,
                                analyze_jit_cost(
                                    self._resume._fn._fn,
                                    self.variables, padded[None], lin,
                                    keep, r - 1,
                                ),
                            )
                        tp = time.perf_counter()
                        while True:
                            try:
                                if self._faults is not None:
                                    self._faults.fire(
                                        "serve.prefill", tick=tick,
                                        request=req.id,
                                        replica=self._replica,
                                    )
                                first_d, cache = self._resume(
                                    self.variables,
                                    jnp.asarray(padded[None]), lin,
                                    keep, r - 1,
                                )
                                # map the shared pages FIRST (the
                                # slot's references keep them alive
                                # through any eviction the remainder
                                # write triggers), then scatter only
                                # the remainder [keep, p)
                                if not self.pool.map_prefix(
                                    slot, entry, keep
                                ):
                                    # entry evicted since the lookup
                                    # (a prior attempt's own page
                                    # pressure): its pages may already
                                    # be free or reallocated, so the
                                    # remainder cache cannot seed the
                                    # slot — fall back to the full
                                    # prefill below
                                    hit = None
                                    keep = 0
                                    break
                                self.pool.write_prefill(
                                    slot, cache, p, start=keep
                                )
                                first = int(first_d[0])
                                break
                            except Exception as e:
                                if is_resource_exhausted(e):
                                    self._note_oom(tick,
                                                   "serve.prefill")
                                elif not is_transient(e):
                                    raise
                                attempts += 1
                                if attempts > self._retry_limit:
                                    break
                                self._backoff(attempts)
                    if hit is None and not adopted:
                        # the miss path — also the landing spot for a
                        # stale-prefix fallback above and a failed
                        # hand-off adoption (attempts carry over into
                        # this loop's retry budget)
                        bucket = self.prefill_bucket(p)
                        padded = np.full((bucket,), self.pad_id,
                                         np.int32)
                        padded[:p] = seq
                        # device analytics: analyze each prefill
                        # bucket's program ONCE, from abstract shapes —
                        # lowering only, no backend compile, no device
                        # work, so the prefill_compile_count pin is
                        # untouched
                        family = f"prefill[{bucket}]"
                        if self.metrics.perf.wants_program(family):
                            self.metrics.perf.register_program(
                                family,
                                analyze_jit_cost(
                                    self._prefill._fn._fn,
                                    self.variables, padded[None], p - 1,
                                ),
                            )
                        tp = time.perf_counter()
                        while True:
                            try:
                                if self._faults is not None:
                                    self._faults.fire(
                                        "serve.prefill", tick=tick,
                                        request=req.id,
                                        replica=self._replica,
                                    )
                                first_d, cache = self._prefill(
                                    self.variables,
                                    jnp.asarray(padded[None]), p - 1,
                                )
                                # only the REAL prompt's K/V enter the
                                # slot; the pad tail of the bucket
                                # cache is dropped here
                                self.pool.write_prefill(slot, cache, p)
                                if self._prefix_cache:
                                    self.pool.prefix_insert(slot, seq)
                                first = int(first_d[0])
                                break
                            except Exception as e:
                                if is_resource_exhausted(e):
                                    self._note_oom(tick,
                                                   "serve.prefill")
                                elif not is_transient(e):
                                    raise
                                attempts += 1
                                if attempts > self._retry_limit:
                                    break
                                self._backoff(attempts)
                if first is None:
                    # retries exhausted: quarantine THIS request only —
                    # the admit loop moves on to the next joiner
                    finished.append(self._quarantine_unactivated(
                        req, slot, tick, "prefill_failed"
                    ))
                    continue
                if self._faults is not None:
                    poison = self._faults.poison_value(
                        "serve.handoff" if adopted else "serve.prefill",
                        tick=tick, request=req.id,
                        replica=self._replica,
                    )
                    if poison is not None:
                        first = int(poison)
                prefill_s = time.perf_counter() - tp
                if adopted:
                    # no program ran: the KV landed by direct write, so
                    # nothing feeds the dispatch analytics — the event
                    # timeline records the adoption instead
                    self.metrics.record_handoff_adopt()
                    if span is not None:
                        span.event(
                            "handoff_adopted", tick=tick, seq_len=p,
                            ms=round(prefill_s * 1e3, 3),
                        )
                    self.recorder.record(
                        "handoff_adopted", tick=tick, id=req.id,
                        seq_len=p, ms=round(prefill_s * 1e3, 3),
                    )
                else:
                    if span is not None:
                        span.event(
                            "prefill", tick=tick, bucket=bucket,
                            ms=round(prefill_s * 1e3, 3), reused=keep,
                        )
                    # the dispatch interval ends at prefill's EXISTING
                    # host sync (int(first_d[0]) above) — analytics
                    # adds none of its own
                    self.metrics.perf.record_dispatch(
                        family, prefill_s, tokens=1
                    )
                    self.recorder.record(
                        "dispatch", tick=tick, family=family,
                        ms=round(prefill_s * 1e3, 3), tokens=1,
                    )
                if not self._token_ok(first):
                    # corrupted first token: quarantine before it can
                    # enter results or seed the decode frontier
                    finished.append(self._quarantine_unactivated(
                        req, slot, tick, "poisoned_token"
                    ))
                    continue
                self.metrics.record_first_token(
                    req, tick, bucket=None if adopted else bucket
                )
                tokens_this_tick += 1
                if self.role == "prefill" and not (
                    len(req.prefix) + 1 >= req.max_new_tokens
                    or (req.eos_id is not None and first == req.eos_id)
                ):
                    # prefill-role terminal (docs/SERVING.md
                    # "Disaggregated fleet"): the slot's work is done —
                    # the raw prefill/resume output cache (rows [0, p)
                    # valid) and the first token ship to a decode
                    # replica via the outbox. The slot frees; under a
                    # prefix cache the inserted entry keeps the pages
                    # alive for future local hits. A request the first
                    # token already FINISHES (budget or EOS) skips the
                    # hand-off and completes here via activate below.
                    self.pool.free(slot)
                    payload = {
                        "id": req.id,
                        "prompt": np.asarray(req.prompt, np.int32),
                        "prefix": np.asarray(req.prefix, np.int32),
                        "length": p,
                        "first_token": int(first),
                        "kv": cache,
                        "max_new_tokens": req.max_new_tokens,
                        "eos_id": req.eos_id,
                        # trace context rides the hand-off: the decode
                        # replica's span carries the SAME id, which is
                        # what lets the hub draw the prefill->decode
                        # flow arrow (checksum covers only the
                        # integrity-bearing fields, so this is free)
                        "trace_id": req.trace_id,
                    }
                    # stamped at PRODUCTION: the adopting replica
                    # re-hashes before writing the cache into a slot,
                    # so wire/at-rest corruption downgrades to the
                    # full-local-prefill fallback instead of silently
                    # poisoning a stream (docs/SERVING.md)
                    payload["checksum"] = integrity.payload_checksum(
                        payload
                    )
                    self._outbox.append(payload)
                    self.recorder.record(
                        "handoff_out", tick=tick, id=req.id, seq_len=p,
                        trace=req.trace_id,
                    )
                    finished.append(
                        self._sched.handoff_result(req, first, tick)
                    )
                    continue
                done = self._sched.activate(slot, req, first, tick)
                if done is not None:
                    finished.append(done)

        if self._sched.filling:
            tokens_this_tick += self._advance_fills(tick, finished)

        # slot occupancy AS OF the decode dispatch: with fused blocks a
        # request can join and retire inside one tick, so sampling after
        # retirement would report empty slots that were busy all block
        leased_this_tick = self.pool.leased_count

        if self._async_host:
            tokens_this_tick += self._decode_phase_async(tick, finished)
        elif self._sched.active:
            tokens_this_tick += self._decode_phase(tick, finished)

        self._sched.tick_count += 1
        tick_s = time.perf_counter() - t0
        self.metrics.sample_tick(
            self._sched.queue_depth, leased_this_tick,
            tick_s, tokens_emitted=tokens_this_tick,
        )
        self.recorder.record(
            "tick", tick=tick, ms=round(tick_s * 1e3, 3),
            tokens=tokens_this_tick,
        )
        for res in finished:
            self.metrics.record_finish(res)
            # a request retired before admission (deadline expiry)
            # abandons any pending hand-off payload
            self._handoffs.pop(res.id, None)
            span = self._spans.pop(res.id, None)
            if span is not None:
                span.end(res.status, tick=res.finish_tick,
                         generated=res.generated)
        # SLO evaluation once per tick, AFTER the finish feed: next
        # tick's admission sees the freshest shed signal
        if self._slo is not None:
            self._slo.evaluate(tick=tick)
        # periodic snapshot cadence (docs/SERVING.md "Replicated
        # serving"): refresh the recovery point every N completed ticks
        # — a shorter cadence re-decodes less after failover, a longer
        # one checkpoints less often
        if (
            self._snapshot_every is not None
            and self._sched.tick_count % self._snapshot_every == 0
        ):
            self.checkpoint()
        return finished

    # -- chunked prefill (docs/SERVING.md "Chunked prefill") ---------------

    def _fresh_carry(self) -> dict:
        """A zeroed batch-1 linear cache spanning the FULL cache_len —
        the chunked fill's carry: every chunk program reads and extends
        it, and its fixed shape keeps chunk programs keyed by the chunk
        bucket alone. Committed REPLICATED under a mesh (mirroring
        ``gather_prefix``) so the chunk jit sees one signature per
        bucket."""
        cache = init_cache(self.graph, self.variables, 1, self.cache_len)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            cache = jax.device_put(
                cache, NamedSharding(self.mesh, PartitionSpec())
            )
        return cache

    def _start_fill(self, req, slot: int, seq, tick: int) -> None:
        """Begin a chunked fill in a freshly leased slot: probe the
        prefix cache (a hit seeds the carry with the shared prefix,
        gathered once) and register the fill frontier with the
        scheduler. No forward pass runs here — ``_advance_fills`` owns
        every chunk dispatch."""
        total = len(seq)
        keep = 0
        entry = None
        hit = (
            self.pool.prefix_lookup(seq, self.chunk_bucket, slot=slot)
            if self._prefix_cache else None
        )
        if hit is not None:
            entry, keep = hit
            carry = self.pool.gather_prefix(entry, keep)
        else:
            carry = self._fresh_carry()
        self._sched.start_fill(
            slot, req, total, keep, {"cache": carry, "entry": entry},
            tick,
        )
        span = self._spans.get(req.id)
        if span is not None:
            span.event("fill_started", tick=tick, total=total,
                       reused=keep)

    def _advance_fills(self, tick: int, finished: list) -> int:
        """Advance every mid-fill slot by ONE bounded chunk dispatch.
        Intermediate chunks are exactly ``prefill_chunk`` wide and
        chain asynchronously (no host sync — the next chunk's inputs
        are the previous chunk's in-flight outputs); a fill's FINAL
        chunk pads to its ladder bucket, lands the carry in the slot
        via ``write_prefill(start=keep)`` and pays the fill's one host
        sync for the first token. Bit-identical to monolithic prefill:
        the chunks recompute the same K/V at the same positions from
        the same tokens, and the final logits slice reads the true
        last-token position. Returns the first tokens emitted by fills
        that completed this tick."""
        tokens = 0
        for slot in sorted(self._sched.filling):
            fs = self._sched.filling[slot]
            req = fs.req
            seq = (
                np.concatenate([req.prompt, req.prefix])
                if len(req.prefix) else req.prompt
            )
            r = fs.total - fs.filled
            final = r <= self._prefill_chunk
            if final:
                bucket = self.chunk_bucket(r)
                # final-chunk WINDOW TRICK: the padded bucket window
                # must not overflow cache_len (a clamped
                # dynamic_update_slice would corrupt earlier carry
                # positions), so slide its start down and RECOMPUTE the
                # overlap [start, filled) — same tokens at the same
                # positions against the same carry prefix produce
                # identical K/V, so the overwrite is a no-op by value
                # and the program width stays on the ladder
                start = min(fs.filled, self.cache_len - bucket)
                width = bucket
                padded = np.full((bucket,), self.pad_id, np.int32)
                padded[: fs.total - start] = seq[start:fs.total]
                last = (fs.total - 1) - start
            else:
                start = fs.filled
                width = self._prefill_chunk
                padded = np.ascontiguousarray(
                    seq[start:start + width], dtype=np.int32
                )
                last = width - 1
            family = f"chunk[{width}]"
            if self.metrics.perf.wants_program(family):
                self.metrics.perf.register_program(
                    family,
                    analyze_jit_cost(
                        self._chunk._fn._fn, self.variables,
                        padded[None], fs.carry["cache"], start, last,
                    ),
                )
            attempts = 0
            tp = time.perf_counter()
            if not final:
                ok = False
                with annotate("serve.prefill"):
                    while True:
                        try:
                            if self._faults is not None:
                                self._faults.fire(
                                    "serve.prefill", tick=tick,
                                    request=req.id,
                                    replica=self._replica,
                                )
                            _tok_d, cache = self._chunk(
                                self.variables,
                                jnp.asarray(padded[None]),
                                fs.carry["cache"], start, last,
                            )
                            # the chunk program is NOT donated: the old
                            # carry survives until this rebind, so a
                            # faulted dispatch retries on intact state
                            fs.carry["cache"] = cache
                            ok = True
                            break
                        except Exception as e:
                            if is_resource_exhausted(e):
                                self._note_oom(tick, "serve.prefill")
                            elif not is_transient(e):
                                raise
                            attempts += 1
                            if attempts > self._retry_limit:
                                break
                            self._backoff(attempts)
                if not ok:
                    self._sched.fill_done(slot)
                    finished.append(self._quarantine_unactivated(
                        req, slot, tick, "prefill_failed"
                    ))
                    continue
                fs.filled += width
                chunk_s = time.perf_counter() - tp
                self.metrics.record_prefill_chunk()
                # no host sync here — the measured interval is
                # enqueue-side only; device-time attribution rides the
                # final chunk's sync
                self.metrics.perf.record_dispatch(family, chunk_s)
                self.recorder.record(
                    "prefill_chunk", tick=tick, id=req.id,
                    filled=fs.filled, total=fs.total,
                    ms=round(chunk_s * 1e3, 3),
                )
                span = self._spans.get(req.id)
                if span is not None:
                    span.event("prefill_chunk", tick=tick,
                               filled=fs.filled, total=fs.total)
                continue

            # -- final chunk: compute, land in the slot, sync ----------
            entry = fs.carry.get("entry")
            first = None
            stale = False
            with annotate("serve.prefill"):
                while True:
                    try:
                        if self._faults is not None:
                            self._faults.fire(
                                "serve.prefill", tick=tick,
                                request=req.id, replica=self._replica,
                            )
                        first_d, cache = self._chunk(
                            self.variables, jnp.asarray(padded[None]),
                            fs.carry["cache"], start, last,
                        )
                        # map the shared prefix pages FIRST (as the
                        # monolithic resume path does), then scatter
                        # only [keep, total)
                        if entry is not None and not self.pool.map_prefix(
                            slot, entry, fs.keep
                        ):
                            stale = True
                            break
                        self.pool.write_prefill(
                            slot, cache, fs.total, start=fs.keep
                        )
                        fs.carry["cache"] = cache
                        first = int(first_d[0])
                        break
                    except Exception as e:
                        if is_resource_exhausted(e):
                            self._note_oom(tick, "serve.prefill")
                        elif not is_transient(e):
                            raise
                        attempts += 1
                        if attempts > self._retry_limit:
                            break
                        self._backoff(attempts)
            if stale:
                # the prefix entry evicted since the fill started: the
                # slot can no longer map pages for [0, keep), so the
                # fill restarts from scratch — the chunked analog of
                # the monolithic stale-hit full-prefill fallback, and
                # equally deterministic (the eventual stream is
                # unchanged)
                fs.filled = 0
                fs.keep = 0
                fs.carry = {"cache": self._fresh_carry(), "entry": None}
                continue
            if first is None:
                self._sched.fill_done(slot)
                finished.append(self._quarantine_unactivated(
                    req, slot, tick, "prefill_failed"
                ))
                continue
            fs.filled = fs.total
            chunk_s = time.perf_counter() - tp
            self.metrics.record_prefill_chunk()
            if self._faults is not None:
                poison = self._faults.poison_value(
                    "serve.prefill", tick=tick, request=req.id,
                    replica=self._replica,
                )
                if poison is not None:
                    first = int(poison)
            if self._prefix_cache and entry is None:
                self.pool.prefix_insert(slot, seq)
            self._sched.fill_done(slot)
            span = self._spans.get(req.id)
            if span is not None:
                span.event(
                    "prefill", tick=tick, bucket=bucket,
                    ms=round(chunk_s * 1e3, 3), reused=fs.keep,
                )
            self.metrics.perf.record_dispatch(family, chunk_s, tokens=1)
            self.recorder.record(
                "dispatch", tick=tick, family=family,
                ms=round(chunk_s * 1e3, 3), tokens=1,
            )
            if not self._token_ok(first):
                finished.append(self._quarantine_unactivated(
                    req, slot, tick, "poisoned_token"
                ))
                continue
            self.metrics.record_first_token(req, tick, bucket=bucket)
            tokens += 1
            if self.role == "prefill" and not (
                len(req.prefix) + 1 >= req.max_new_tokens
                or (req.eos_id is not None and first == req.eos_id)
            ):
                # prefill-role hand-off fires at FILL COMPLETION: the
                # carry's rows [0, total) are exactly the monolithic
                # prefill output the payload contract expects
                self.pool.free(slot)
                payload = {
                    "id": req.id,
                    "prompt": np.asarray(req.prompt, np.int32),
                    "prefix": np.asarray(req.prefix, np.int32),
                    "length": fs.total,
                    "first_token": int(first),
                    "kv": fs.carry["cache"],
                    "max_new_tokens": req.max_new_tokens,
                    "eos_id": req.eos_id,
                    "trace_id": req.trace_id,
                }
                payload["checksum"] = integrity.payload_checksum(
                    payload
                )
                self._outbox.append(payload)
                self.recorder.record(
                    "handoff_out", tick=tick, id=req.id,
                    seq_len=fs.total, trace=req.trace_id,
                )
                finished.append(
                    self._sched.handoff_result(req, first, tick)
                )
                continue
            done = self._sched.activate(slot, req, first, tick)
            if done is not None:
                finished.append(done)
        return tokens

    # -- pipelined async host loop (docs/SERVING.md "Async host loop") -----

    def _decode_phase_async(self, tick: int, finished: list) -> int:
        """One PIPELINED decode round: dispatch this tick's block N+1
        behind the in-flight block N, then fetch N's tokens — the host
        bookkeeping between the two (and the whole admit/fill phase
        before them) overlaps into N's device execution. At most one
        host sync per block, exactly as the synchronous loop, but the
        sync lands one tick late and rarely blocks. Token streams are
        bit-identical to the synchronous engine: dispatch inputs are
        derived from device-side state (in-flight last tokens selected
        on device) plus conservative host budget views, and the fetch's
        identity fence drops any row whose slot changed hands after
        dispatch."""
        prev = self._inflight
        self._inflight = None
        status = self._dispatch_block(tick, prev)
        n_tokens = self._fetch_inflight(prev, tick, finished)
        if status == "failed":
            # the batch stayed undispatchable through retries AND
            # degradation — quarantine what is left of it, AFTER the
            # previous block's tokens were committed above
            for slot in list(self._sched.active):
                finished.append(self._quarantine_slot(
                    slot, tick, "decode_failed"
                ))
        if self._inflight is not None and not self._sched.busy:
            # every request retired at the fetch above (e.g. EOS swept
            # the batch) while a speculative block is still in flight:
            # drain it now — its rows all fail the identity fence, so
            # it contributes nothing, but run() must not exit with an
            # open deferred-free window
            inf, self._inflight = self._inflight, None
            n_tokens += self._fetch_inflight(inf, tick, finished)
        return n_tokens

    def _dispatch_block(self, tick: int, prev: dict | None) -> str:
        """Dispatch one fused decode block WITHOUT fetching it (async
        mode). Returns ``"ok"`` (in-flight record stored), ``"idle"``
        (nothing to dispatch: no active slots, or every active slot's
        budget may already exhaust inside ``prev``) or ``"failed"``
        (retries exhausted).

        The pipelining contract, input by input:

        * last tokens — the host's view lags for slots riding ``prev``,
          so their rows select ``prev``'s final emitted token ON DEVICE
          (``jnp.where`` over the in-flight output; async, no sync).
        * remaining budgets — reduced by ``prev``'s block size for
          in-flight slots (the conservative view). A slot whose
          adjusted budget is <= 0 either retires at ``prev``'s fetch
          (its rows here are dropped by the identity fence) or was
          going to die on device anyway; the block-size clamp uses only
          POSITIVE adjusted budgets, so no surviving stream can overrun
          its budget mid-block — the same parity rule as the
          synchronous loop.
        * page frontiers — advanced by ``prev``'s block size before
          ``ensure_decode_pages``, covering the writes the in-flight
          block may still land.
        """
        attempts = 0
        while self._sched.active:
            states = dict(self._sched.active)
            lag = {}
            if prev is not None:
                for slot, st in prev["states"].items():
                    if states.get(slot) is st:
                        lag[slot] = prev["t_block"]
            pre_pos = {
                slot: st.pos + lag.get(slot, 0)
                for slot, st in states.items()
            }
            tok, rem, eos, _ = self._sched.decode_block_inputs(
                self.pad_id
            )
            rems = []
            for slot, st in states.items():
                adj = (
                    st.req.max_new_tokens - len(st.out)
                    - lag.get(slot, 0)
                )
                rem[slot] = adj
                if adj > 0:
                    rems.append(adj)
            if not rems:
                return "idle"
            t_block = self._block_size(min(rems))
            slot_sh = None
            if self.mesh is not None:
                slot_sh = self.pool.slot_sharding
                tok_d = jax.device_put(jnp.asarray(tok), slot_sh)
                rem_d = jax.device_put(jnp.asarray(rem), slot_sh)
                eos_d = jax.device_put(jnp.asarray(eos), slot_sh)
            else:
                tok_d, rem_d, eos_d = (
                    jnp.asarray(tok), jnp.asarray(rem), jnp.asarray(eos)
                )
            if lag:
                sel = np.zeros((self.pool.num_slots,), bool)
                for slot in lag:
                    sel[slot] = True
                sel_d = jnp.asarray(sel)
                tok_d = jnp.where(sel_d, prev["toks"][:, -1], tok_d)
                if slot_sh is not None:
                    # re-commit the selected vector so the jit sees the
                    # pinned signature every tick
                    tok_d = jax.device_put(tok_d, slot_sh)
            family = f"decode[T={t_block}]"
            if self.metrics.perf.wants_program(family):
                self.metrics.perf.register_program(
                    family,
                    analyze_jit_cost(
                        self._decode._fn._fn, self.variables,
                        self.pool.buffers, self.pool.positions,
                        self.pool.live, tok_d, rem_d, eos_d, t_block,
                    ),
                )
            try:
                with annotate("serve.decode"):
                    issued = time.perf_counter()
                    if self._paged:
                        self.pool.ensure_decode_pages(pre_pos, t_block)
                    if self._faults is not None:
                        self._faults.fire("serve.decode", tick=tick,
                                          replica=self._replica)
                    # the live vector is DONATED into this dispatch,
                    # but when it is also the in-flight block's fetch
                    # target (prev's output) donation would delete it
                    # before prev's device_get — donate a copy instead
                    # (S bools; async, ordered after prev)
                    live_in = self.pool.live
                    if prev is not None:
                        live_in = jnp.copy(live_in)
                    toks, live, buffers, positions = self._decode(
                        self.variables, self.pool.buffers,
                        self.pool.positions, live_in,
                        tok_d, rem_d, eos_d, t_block,
                    )
                    self.pool.buffers = buffers
                    self.pool.positions = positions
                    self.pool.live = live
            except Exception as e:
                if is_resource_exhausted(e):
                    self._note_oom(tick, "serve.decode")
                elif not is_transient(e):
                    raise
                attempts += 1
                if attempts > self._retry_limit:
                    return "failed"
                self._backoff(attempts)
                continue
            self._dispatch_gen += 1
            self.pool.defer_frees(self._dispatch_gen)
            self._inflight = {
                "toks": toks, "live": live, "states": states,
                "pre_pos": pre_pos, "t_block": t_block,
                "family": family, "issued": issued,
                "gen": self._dispatch_gen, "tick": tick,
                "n_active": len(states),
                "overlapped": prev is not None,
            }
            if prev is not None:
                self.metrics.record_overlapped_dispatch()
            return "ok"
        return "idle"

    def _fetch_inflight(self, inflight: dict | None, tick: int,
                        finished: list) -> int:
        """Fetch and consume one previously dispatched block (async
        mode): the block's ONE host sync, then the same poison/
        validation/consume/accounting pipeline as the synchronous
        loop — except every row passes the IDENTITY FENCE (the slot
        must still hold the request captured at dispatch) and the
        pools' deferred frees stamped up to this block's generation
        flush afterwards."""
        if inflight is None:
            if self._inflight is None:
                # nothing in flight in either direction: close the
                # deferred-free window so frees turn immediate again
                self.pool.flush_frees(None)
            return 0
        states = inflight["states"]
        pre_pos = inflight["pre_pos"]
        t_block = inflight["t_block"]
        family = inflight["family"]
        n_active = inflight["n_active"]

        def _live_rows():
            return [
                s for s, st in states.items()
                if self._sched.active.get(s) is st
            ]

        toks_h = live_h = None
        fetch_attempts = 0
        wait0 = time.perf_counter()
        while True:
            try:
                if self._faults is not None:
                    self._faults.fire("serve.device_get", tick=tick,
                                      replica=self._replica)
                toks_h, live_h = jax.device_get(
                    (inflight["toks"], inflight["live"])
                )
                break
            except Exception as e:
                if not (is_transient(e) or is_resource_exhausted(e)):
                    raise
                fetch_attempts += 1
                if fetch_attempts > self._retry_limit:
                    break
                self._backoff(fetch_attempts)
        done = time.perf_counter()
        self.metrics.record_host_sync(done - wait0)
        prev_done = self._prev_block_done
        self._prev_block_done = done
        if toks_h is None:
            for slot in _live_rows():
                finished.append(self._quarantine_slot(
                    slot, tick, "device_get_failed"
                ))
            self.pool.flush_frees(inflight["gen"])
            if self._inflight is None:
                self.pool.flush_frees(None)
            return 0

        # queued-vs-executing attribution: a pipelined block could not
        # START before the previous block's outputs materialized (its
        # inputs are that block's donated buffers), so the span from
        # issue to the previous fetch's completion is queue time, not
        # device time — core/perf.py subtracts it from device_s so MFU
        # and bandwidth figures stay honest under pipelining
        dispatch_s = done - inflight["issued"]
        queued_s = 0.0
        if inflight["overlapped"]:
            queued_s = min(
                dispatch_s, max(0.0, prev_done - inflight["issued"])
            )
        toks_h = np.asarray(toks_h)
        if toks_h.ndim == 1:
            toks_h = toks_h[:, None]
        if self._faults is not None:
            toks_h = self._faults.poison_block(
                "serve.device_get", toks_h, tick=tick,
                slots=_live_rows(), replica=self._replica,
            )
        bad_rows = (toks_h < 0).any(axis=1)
        if self._vocab is not None:
            bad_rows |= (toks_h >= int(self._vocab)).any(axis=1)
        quarantined: set[int] = set()
        if bad_rows.any():
            for slot in _live_rows():
                if bad_rows[slot]:
                    finished.append(self._quarantine_slot(
                        slot, tick, "poisoned_token"
                    ))
                    quarantined.add(slot)

        blk_finished, consumed = self._sched.consume(
            toks_h, tick, states=states
        )
        n_tokens = sum(consumed.values())
        live_kv = sum(
            c * (pre_pos[slot] + 1) + c * (c - 1) // 2
            for slot, c in consumed.items()
        )
        exec_s = max(0.0, dispatch_s - queued_s)
        self.metrics.record_decode(
            n_active, exec_s, tokens_emitted=n_tokens,
            block=t_block, live_kv=live_kv, cache_len=self.cache_len,
        )
        self.metrics.perf.record_dispatch(
            family, dispatch_s, tokens=n_tokens, queued_s=queued_s,
        )
        self.recorder.record(
            "dispatch", tick=tick, family=family,
            ms=round(exec_s * 1e3, 3),
            queued_ms=round(queued_s * 1e3, 3), tokens=n_tokens,
        )
        if __debug__:
            # device/host parity holds row by row for every request
            # that kept its slot from dispatch to fetch — rows the
            # identity fence dropped (consume skipped them) and
            # quarantined rows are exempt, mirroring the synchronous
            # loop's quarantine exemption
            for slot, st in states.items():
                if slot in quarantined or consumed.get(slot) is None:
                    continue
                assert bool(live_h[slot]) == (
                    self._sched.active.get(slot) is st
                ), (
                    f"device live mask and host retirement disagree "
                    f"for slot {slot} (async block T={t_block})"
                )
        decode_ms = round(exec_s * 1e3, 3)
        for slot, st in states.items():
            if consumed.get(slot) is None:
                continue
            span = self._spans.get(st.req.id)
            if span is not None:
                span.event("decode", tick=tick, pos=pre_pos[slot],
                           n_active=n_active, block=t_block,
                           tokens=consumed.get(slot, 0),
                           step_ms=decode_ms)
        finished.extend(blk_finished)
        self._note_clean_dispatch(tick)
        self.pool.flush_frees(inflight["gen"])
        if self._inflight is None:
            self.pool.flush_frees(None)
        return n_tokens

    def _decode_phase(self, tick: int, finished: list) -> int:
        """One fused decode BLOCK for all active slots, behind the
        resilience layer: transient dispatch errors retry with capped
        deterministic backoff, RESOURCE_EXHAUSTED degrades (smaller
        ladder block, tighter admission, preemption at the floor) and
        retries, and a dispatch that stays impossible quarantines the
        remaining batch — every request gets a definite terminal status
        instead of wedging ``run()``. Appends terminal results to
        ``finished``; returns the real tokens consumed this tick."""
        attempts = 0
        while self._sched.active:
            n_active = len(self._sched.active)
            states = list(self._sched.active.items())
            # write positions BEFORE the block: consume() advances the
            # host mirrors, and the live-KV accounting below needs the
            # per-slot starting frontier. Rebuilt on every retry: an
            # OOM response may have shrunk the block cap or preempted a
            # slot since the failed attempt.
            pre_pos = {slot: st.pos for slot, st in states}
            tok, rem, eos, min_rem = self._sched.decode_block_inputs(
                self.pad_id
            )
            t_block = self._block_size(min_rem)
            if self.mesh is not None:
                # commit the host-built per-tick vectors to the data
                # axis (device_put: a scatter, NOT a host sync) so every
                # tick presents the decode block one fixed signature
                slot_sh = self.pool.slot_sharding
                tok_d = jax.device_put(jnp.asarray(tok), slot_sh)
                rem_d = jax.device_put(jnp.asarray(rem), slot_sh)
                eos_d = jax.device_put(jnp.asarray(eos), slot_sh)
            else:
                tok_d, rem_d, eos_d = (
                    jnp.asarray(tok), jnp.asarray(rem), jnp.asarray(eos)
                )
            # device analytics: analyze each ladder size's program ONCE
            # from abstract shapes, BEFORE the dispatch donates the pool
            # buffers (ShapeDtypeStruct conversion reads only
            # shape/dtype and keeps no buffer references). Lowering
            # fires no backend compile, so the decode_compile_count pin
            # and the watchdog budget are untouched.
            family = f"decode[T={t_block}]"
            if self.metrics.perf.wants_program(family):
                self.metrics.perf.register_program(
                    family,
                    analyze_jit_cost(
                        self._decode._fn._fn, self.variables,
                        self.pool.buffers, self.pool.positions,
                        self.pool.live, tok_d, rem_d, eos_d, t_block,
                    ),
                )
            try:
                with annotate("serve.decode"):
                    td = time.perf_counter()
                    # paged pool: pre-map every page this block can
                    # write (the tables are read-only DURING the block,
                    # preserving its one host sync). Page exhaustion
                    # raises RESOURCE_EXHAUSTED inside this try, so it
                    # walks the same ladder as a real allocator OOM —
                    # and the preemption it can trigger FREES pages.
                    if self._paged:
                        self.pool.ensure_decode_pages(pre_pos, t_block)
                    # the fault hook fires BEFORE the dispatch: an
                    # injected failure never consumes the donated
                    # buffers, so retrying with the same pool state is
                    # always safe
                    if self._faults is not None:
                        self._faults.fire("serve.decode", tick=tick,
                                          replica=self._replica)
                    toks, live, buffers, positions = self._decode(
                        self.variables, self.pool.buffers,
                        self.pool.positions, self.pool.live,
                        tok_d, rem_d, eos_d, t_block,
                    )
                    # the inputs were DONATED: rebind the pool's device
                    # state (buffers AND positions/live) to the block's
                    # outputs before anything can touch stale references
                    self.pool.buffers = buffers
                    self.pool.positions = positions
                    self.pool.live = live
            except Exception as e:
                if is_resource_exhausted(e):
                    self._note_oom(tick, "serve.decode")
                elif not is_transient(e):
                    raise
                attempts += 1
                if attempts > self._retry_limit:
                    # the batch stayed undispatchable through retries
                    # AND degradation: quarantine what is left of it
                    for slot, _st in states:
                        if slot in self._sched.active:
                            finished.append(self._quarantine_slot(
                                slot, tick, "decode_failed"
                            ))
                    return 0
                self._backoff(attempts)
                continue

            # the dispatch SUCCEEDED and the pool is rebound, so the
            # fetch gets its OWN retry loop — re-dispatching here would
            # decode past this block and skip its tokens
            toks_h = live_h = None
            fetch_attempts = 0
            wait0 = time.perf_counter()
            while True:
                try:
                    if self._faults is not None:
                        self._faults.fire("serve.device_get", tick=tick,
                                          replica=self._replica)
                    # the ONE host sync per block: (S, T) tokens + the
                    # per-slot finished vector come back together
                    toks_h, live_h = jax.device_get((toks, live))
                    break
                except Exception as e:
                    if not (is_transient(e) or is_resource_exhausted(e)):
                        raise
                    fetch_attempts += 1
                    if fetch_attempts > self._retry_limit:
                        break
                    self._backoff(fetch_attempts)
            decode_s = time.perf_counter() - td
            # the sync loop pays its block's full device time here —
            # the host-idle numerator the async loop exists to shrink
            self.metrics.record_host_sync(time.perf_counter() - wait0)
            if toks_h is None:
                # the block's tokens are unrecoverable on host: every
                # active stream now has a gap — definite failure beats
                # silently resuming with missing tokens
                for slot, _st in states:
                    if slot in self._sched.active:
                        finished.append(self._quarantine_slot(
                            slot, tick, "device_get_failed"
                        ))
                return 0

            toks_h = np.asarray(toks_h)
            if toks_h.ndim == 1:
                toks_h = toks_h[:, None]
            if self._faults is not None:
                toks_h = self._faults.poison_block(
                    "serve.device_get", toks_h, tick=tick,
                    slots=[s for s, _ in states
                           if s in self._sched.active],
                    replica=self._replica,
                )
            # token-stream validation (always on — one vectorized pass
            # over an (S, T) int block): greedy tokens are argmax
            # indices in [0, vocab), so anything else is corruption;
            # quarantine the row BEFORE consume() folds it into results
            bad_rows = (toks_h < 0).any(axis=1)
            if self._vocab is not None:
                bad_rows |= (toks_h >= int(self._vocab)).any(axis=1)
            quarantined: set[int] = set()
            if bad_rows.any():
                for slot, _st in states:
                    if slot in self._sched.active and bad_rows[slot]:
                        finished.append(self._quarantine_slot(
                            slot, tick, "poisoned_token"
                        ))
                        quarantined.add(slot)

            blk_finished, consumed = self._sched.consume(toks_h, tick)
            n_tokens = sum(consumed.values())
            # live KV rows the block actually attended, per slot: its
            # c consumed micro-steps read frontiers pos0+1 .. pos0+c
            # (an arithmetic series) — vs the c * cache_len rows a
            # dense read would touch, the FLOP-utilization figure
            live_kv = sum(
                c * (pre_pos[slot] + 1) + c * (c - 1) // 2
                for slot, c in consumed.items()
            )
            self.metrics.record_decode(
                n_active, decode_s, tokens_emitted=n_tokens,
                block=t_block, live_kv=live_kv, cache_len=self.cache_len,
            )
            # the dispatch interval spans issue -> the block's ONE
            # existing device_get; analytics adds no sync of its own
            self.metrics.perf.record_dispatch(
                family, decode_s, tokens=n_tokens
            )
            self.recorder.record(
                "dispatch", tick=tick, family=family,
                ms=round(decode_s * 1e3, 3), tokens=n_tokens,
            )
            if __debug__:
                # the device live mask and the host's retirement
                # bookkeeping must agree slot for slot — the parity
                # contract's cheap runtime cross-check (quarantined
                # slots are exempt: the host retired them while the
                # fetched mask still shows them live)
                for slot, _st in states:
                    if slot in quarantined:
                        continue
                    assert bool(live_h[slot]) == (
                        slot in self._sched.active
                    ), (
                        f"device live mask and host retirement disagree "
                        f"for slot {slot} (block T={t_block})"
                    )
            decode_ms = round(decode_s * 1e3, 3)
            for slot, st in states:
                span = self._spans.get(st.req.id)
                if span is not None:
                    span.event("decode", tick=tick, pos=pre_pos[slot],
                               n_active=n_active, block=t_block,
                               tokens=consumed.get(slot, 0),
                               step_ms=decode_ms)
            finished.extend(blk_finished)
            self._note_clean_dispatch(tick)
            return n_tokens
        return 0

    def run(self, max_ticks: int = 100_000) -> dict[int, RequestResult]:
        """Step until queue and slots drain; results keyed by request
        id. ``max_ticks`` bounds runaway loops (a generator that never
        emits EOS still retires at its token budget, so hitting the
        bound means a caller bug — reported as the typed error). The
        error does NOT discard work: completed results ride on it as
        ``err.results``, alongside every still-pending request retired
        with the definite status ``"stalled"`` — and the engine is
        drained afterwards, not wedged."""
        results: dict[int, RequestResult] = {}
        start = self.tick
        # black-box contract: the flight recorder dumps its last N
        # events to the error log automatically when the typed error
        # escapes — the post-mortem for "what was the engine doing"
        with self.recorder.dump_on_friendly_error():
            while self._sched.busy:
                if self.tick - start >= max_ticks:
                    n_queued = self._sched.queue_depth
                    n_active = len(self._sched.active)
                    # abandon any in-flight pipelined block and close
                    # the deferred-free window so the stall's slot
                    # frees land immediately
                    self._inflight = None
                    self.pool.flush_frees(None)
                    for res in self._sched.stall_pending(self.tick):
                        results[res.id] = res
                        self.metrics.record_finish(res)
                        span = self._spans.pop(res.id, None)
                        if span is not None:
                            span.end(res.status, tick=res.finish_tick,
                                     generated=res.generated)
                    err = FriendlyError(
                        f"serve run() exceeded max_ticks ({max_ticks}) "
                        f"with {n_queued} queued and "
                        f"{n_active} active requests; partial results "
                        "(completed + 'stalled') are attached as "
                        "err.results"
                    )
                    err.results = results
                    raise err
                for res in self.step():
                    results[res.id] = res
        return results

    # -- replica control plane (serve/supervisor.py drives these) ----------

    @property
    def queue_full(self) -> bool:
        """True when the next ``submit`` would bounce off admission
        control — the supervisor's router checks this before choosing a
        replica."""
        return self._sched.queue_depth >= self._sched.max_queue

    def cancel(self, request_id: int) -> int | None:
        """Cancel one pending request WITHOUT a terminal result: the
        hedge loser's exit (first-committed-wins — the winning replica
        already committed the stream, this copy's tokens are waste) and
        failover dedup. Queued entries leave the queue; active ones
        free their slot. Returns the emitted-token count discarded, or
        None when the id is unknown/terminal (or the engine is dead —
        its resources are already parked)."""
        if self._dead:
            return None
        emitted = self._sched.cancel(request_id)
        if emitted is None:
            return None
        self._handoffs.pop(request_id, None)
        self.metrics.record_cancel()
        span = self._spans.pop(request_id, None)
        if span is not None:
            span.end("cancelled", tick=self.tick)
        self.recorder.record(
            "cancelled", tick=self.tick, id=request_id, emitted=emitted,
        )
        return emitted

    def steal_all(self) -> list[dict]:
        """Hand off EVERY pending request for migration to another
        replica (zero-loss drain, or stall cleanup): active slots
        preempt — their emitted tokens fold into resume prefixes and
        their slots free — then the queue drains in FIFO order.
        Returns plain payload dicts for :meth:`adopt` on the target
        engine; re-prefilling prompt + prefix there continues each
        stream bit-identically (greedy determinism)."""
        reqs = self._sched.handoff_all() if not self._dead else []
        out = []
        for req in reqs:
            # a stolen request's pending KV payload stays behind: the
            # adopting engine re-prefills from the prompt instead
            self._handoffs.pop(req.id, None)
            out.append({
                "id": req.id,
                "prompt": np.asarray(req.prompt, np.int32),
                "prefix": np.asarray(req.prefix, np.int32),
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id,
                "trace_id": req.trace_id,
            })
            span = self._spans.pop(req.id, None)
            if span is not None:
                span.end("migrated", tick=self.tick,
                         prefix_len=len(req.prefix))
        if out:
            self.recorder.record("handoff", tick=self.tick, n=len(out))
        return out

    def adopt(self, prompt, *, prefix=(), max_new_tokens: int,
              eos_id: int | None = None,
              trace_id: str | None = None) -> int:
        """Admit a request MIGRATED from another replica (drain
        hand-off or failover re-route): ``prefix`` is the tokens the
        source replica already emitted, re-prefilled with the prompt so
        decode resumes exactly where it stopped and accepted tokens are
        never re-emitted. Bypasses ``max_queue`` — the request was
        admitted once already; bouncing it now would turn migration
        into data loss. Returns the new engine-local id."""
        prompt = np.asarray(prompt, np.int32)
        prefix = np.asarray(prefix, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise FriendlyError(
                f"adopt needs a non-empty 1-D prompt, got shape "
                f"{prompt.shape}"
            )
        if len(prefix) >= max_new_tokens:
            raise FriendlyError(
                f"adopted prefix ({len(prefix)} tokens) already meets "
                f"the request budget ({max_new_tokens}); the source "
                "replica should have retired it as completed"
            )
        if int(prompt.size) + max_new_tokens > self.cache_len:
            raise FriendlyError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds this engine's cache_len "
                f"({self.cache_len}); migrate to a replica with equal "
                "cache geometry"
            )
        req = ServeRequest(
            id=self._next_id,
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_id=eos_id,
            deadline_tick=None,
            submit_tick=self.tick,
            submit_wall=time.perf_counter(),
            prefix=prefix,
            trace_id=trace_id or f"t{self._next_id}",
        )
        self._sched.queue.append(req)
        self._next_id += 1
        self.metrics.record_submit()
        span = self._tracer.span(
            "request", tick=self.tick, id=req.id, trace=req.trace_id,
            prompt_len=int(prompt.size), max_new_tokens=max_new_tokens,
        )
        span.event("adopted", tick=self.tick, prefix_len=len(prefix))
        self._spans[req.id] = span
        return req.id

    def take_handoffs(self) -> list[dict]:
        """Drain the prefill-role outbox: every KV hand-off payload
        produced since the last call, in hand-off order. Returns []
        on a dead engine — its payloads are unreachable and the fleet
        re-routes those requests from its own ledger (re-prefill,
        bit-identical by greedy determinism)."""
        if self._dead:
            return []
        out, self._outbox = self._outbox, []
        return out

    def adopt_handoff(self, payload: dict) -> int:
        """Admit a cross-replica KV hand-off payload (the dicts
        :meth:`take_handoffs` returns, routed here by
        ``serve/fleet.py``): like :meth:`adopt`, but carrying the
        source replica's prefill output cache plus the first token, so
        admission lands the KV by DIRECT write into the leased slot —
        no prefill program runs here and the continued stream is
        bit-identical to a local prefill. The write travels the
        ``serve.handoff`` fault hook; a payload that cannot land falls
        back to a full local prefill. Returns the new engine-local
        id."""
        prompt = np.asarray(payload["prompt"], np.int32)
        prefix = np.asarray(payload.get("prefix", ()), np.int32)
        max_new_tokens = int(payload["max_new_tokens"])
        if prompt.ndim != 1 or prompt.size == 0:
            raise FriendlyError(
                f"hand-off payload needs a non-empty 1-D prompt, got "
                f"shape {prompt.shape}"
            )
        if len(prefix) + 1 > max_new_tokens:
            raise FriendlyError(
                f"hand-off prefix ({len(prefix)} tokens) + the first "
                f"token exceed the request budget ({max_new_tokens}); "
                "the prefill replica should have completed it locally"
            )
        if int(payload["length"]) != int(prompt.size) + len(prefix):
            raise FriendlyError(
                f"hand-off payload length ({payload['length']}) does "
                f"not match prompt ({prompt.size}) + prefix "
                f"({len(prefix)}); the payload is torn"
            )
        if int(prompt.size) + max_new_tokens > self.cache_len:
            raise FriendlyError(
                f"prompt ({prompt.size}) + max_new_tokens "
                f"({max_new_tokens}) exceeds this engine's cache_len "
                f"({self.cache_len}); hand off to a replica with equal "
                "cache geometry"
            )
        req = ServeRequest(
            id=self._next_id,
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_id=payload.get("eos_id"),
            deadline_tick=None,
            submit_tick=self.tick,
            submit_wall=time.perf_counter(),
            prefix=prefix,
            # the producing replica's trace context survives adoption:
            # the continued stream's span here joins the prefill span
            # there on one id
            trace_id=str(payload.get("trace_id") or f"t{self._next_id}"),
        )
        self._sched.queue.append(req)
        self._handoffs[req.id] = dict(payload)
        self._next_id += 1
        self.metrics.record_submit()
        span = self._tracer.span(
            "request", tick=self.tick, id=req.id, trace=req.trace_id,
            prompt_len=int(prompt.size), max_new_tokens=max_new_tokens,
        )
        span.event("handoff_queued", tick=self.tick,
                   seq_len=int(payload["length"]))
        self._spans[req.id] = span
        return req.id

    def health_counters(self) -> dict:
        """The supervisor's probe surface: liveness/readiness inputs in
        one cheap host-side dict (no device sync) — tick progress,
        queue/slot load, degradation, SLO burn, and the fault/retry
        totals the health model scores."""
        return {
            "tick": self.tick,
            "busy": self.busy,
            "dead": self._dead,
            "role": self.role,
            "queue_depth": self.queue_depth,
            "active": len(self._sched.active),
            "filling": len(self._sched.filling),
            "degraded": self.degraded,
            "slo_burning": (
                bool(self._slo.should_shed)
                if self._slo is not None else False
            ),
            # consecutive burning SLO evaluations — the fleet
            # autoscaler's scale-up signal (serve/fleet.py)
            "slo_burn_ticks": (
                int(self._slo.burn_ticks)
                if self._slo is not None else 0
            ),
            "retries_total": self.metrics.retries_total,
            "quarantined_total": self.metrics.quarantined_total,
            "faults_injected_total": self.metrics.faults_injected_total,
            "tokens_generated": self.metrics.tokens_generated,
        }

    def _park_after_kill(self) -> None:
        """Deterministic device-resource parking for a killed engine:
        every leased slot frees back to the pool — on a paged pool that
        releases the slot's page mappings (refcounts drop; pages return
        to the free lists, or survive only under prefix-cache
        references) — so an in-process supervisor restoring this
        engine's snapshot onto a fresh engine never double-holds
        device state. Host request bookkeeping is kept for post-mortem
        snapshots; the engine refuses further steps."""
        if self._dead:
            return
        self._dead = True
        # undelivered hand-off payloads are unreachable on a dead
        # engine; the fleet re-routes those requests from its ledger
        self._outbox.clear()
        # an in-flight pipelined block dies with the engine: drop the
        # record and close the deferred-free window so every leased
        # slot below releases immediately
        self._inflight = None
        self.pool.flush_frees(None)
        leased = self.pool.leased_slots()
        for slot in leased:
            self.pool.free(slot)
        self.recorder.record(
            "killed", tick=self.tick, parked_slots=len(leased),
        )

    # -- checkpoint / restore ----------------------------------------------

    @property
    def last_snapshot(self) -> dict | None:
        """The most recent COMPLETE periodic checkpoint (see
        ``snapshot_every_ticks`` / :meth:`checkpoint`) — the
        supervisor's recovery point. A checkpoint that failed mid-write
        never lands here."""
        return self._last_snapshot

    def checkpoint(self) -> dict | None:
        """Take one periodic checkpoint through the ``serve.snapshot``
        fault hook. A fault here models a checkpoint failing MID-WRITE:
        the torn snapshot is NOT restorable, so ``last_snapshot`` keeps
        the previous complete one and serving continues (the failure is
        counted + recorded). Returns the new snapshot dict, or None
        when the write failed. An injected ``kill`` at the snapshot
        site is a crash during checkpointing — it parks and re-raises
        like any other kill."""
        try:
            if self._faults is not None:
                self._faults.fire("serve.snapshot", tick=self.tick,
                                  replica=self._replica)
            snap = self.snapshot()
        except EngineKilled:
            self._park_after_kill()
            raise
        except Exception as e:  # noqa: BLE001 — a torn checkpoint must
            # not take serving down; the engine keeps the previous one
            self.metrics.record_snapshot_failure()
            self.recorder.record(
                "snapshot_failed", tick=self.tick, error=str(e),
            )
            return None
        if self._faults is not None:
            # the serve.snapshot silent-corruption drill: the flip
            # lands AFTER the checksum stamp, so the damage is latent
            # until a restore re-hashes the snapshot
            cseed = self._faults.corrupt_spec(
                "serve.snapshot", tick=self.tick, replica=self._replica
            )
            if cseed is not None:
                snap = integrity.flip_bit_json(snap, cseed)
        self._last_snapshot = snap
        self.metrics.record_snapshot()
        self.recorder.record(
            "snapshot", tick=self.tick,
            active=len(snap["active"]), queued=len(snap["queued"]),
        )
        return snap

    def snapshot(self) -> dict:
        """JSON-able checkpoint of ALL host-side request state: every
        queued and active request's prompt, emitted tokens, budget,
        deadline, and the engine tick. Deliberately NO device state —
        restore re-prefills prompt + emitted prefix, and greedy decode
        makes the rebuilt KV frontier (and every post-restore token)
        bit-identical to the uncrashed run, so the checkpoint stays
        tiny and device-layout-agnostic (a single-device snapshot
        restores onto a mesh engine, and vice versa). Call between
        ``step()``s; hand the dict to :meth:`restore` after a crash."""
        active = []
        for slot, st in sorted(self._sched.active.items()):
            req = st.req
            active.append({
                "id": req.id,
                "prompt": [int(x) for x in req.prompt],
                "emitted": [int(x) for x in st.out],
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id,
                "deadline_tick": req.deadline_tick,
                "submit_tick": req.submit_tick,
                "trace": req.trace_id,
            })
        queued = []
        # mid-fill requests checkpoint as queued entries with their
        # resume prefix: restore re-prefills from scratch, and since a
        # chunked fill emits no tokens before completion there is no
        # partial-fill state worth carrying — determinism does the rest
        for _slot, fs in sorted(self._sched.filling.items()):
            req = fs.req
            queued.append({
                "id": req.id,
                "prompt": [int(x) for x in req.prompt],
                "emitted": [int(x) for x in req.prefix],
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id,
                "deadline_tick": req.deadline_tick,
                "submit_tick": req.submit_tick,
                "trace": req.trace_id,
            })
        for req in self._sched.queue:
            queued.append({
                "id": req.id,
                "prompt": [int(x) for x in req.prompt],
                "emitted": [int(x) for x in req.prefix],
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id,
                "deadline_tick": req.deadline_tick,
                "submit_tick": req.submit_tick,
                "trace": req.trace_id,
            })
        snap = {
            "version": 1,
            "model": self.graph.name,
            "cache_len": self.cache_len,
            "pad_id": self.pad_id,
            "tick": self.tick,
            "next_id": self._next_id,
            "active": active,
            "queued": queued,
        }
        if self._paged:
            # paging plane (page tables, refcounts, prefix entries):
            # informational — restore() re-prefills and rebuilds the
            # mappings from scratch, but the crash dump stays auditable
            # (refcount totals vs mapped pages)
            snap["paging"] = self.pool.snapshot()
        # canonical-JSON self-checksum: restore() re-hashes and rejects
        # a snapshot whose bytes changed at rest (SnapshotCorruption)
        snap["checksum"] = integrity.json_checksum(snap)
        return snap

    @classmethod
    def restore(cls, snapshot: dict, graph, variables,
                **kwargs) -> "ServeEngine":
        """Rebuild a crashed engine from :meth:`snapshot`: a fresh
        engine (same graph/variables; ``kwargs`` as for the
        constructor) whose queue re-admits every checkpointed request —
        active ones first, carrying their emitted tokens as a resume
        prefix, so re-prefilling prompt + prefix continues each stream
        bit-identically (the crash drill in tests/test_serve_faults.py
        is the proof). Deadlines and the tick counter are absolute and
        survive the rebuild.

        A snapshot that carries a ``checksum`` stamp is re-hashed
        FIRST: a mismatch raises
        :class:`~mmlspark_tpu.core.integrity.SnapshotCorruption` naming
        both hashes before any engine state is rebuilt — the caller
        (the fleet's failover) falls back to a fresh engine + request
        re-admission rather than resuming from lying state."""
        stamp = snapshot.get("checksum")
        if stamp is not None:
            actual = integrity.json_checksum(snapshot)
            if actual != stamp:
                raise SnapshotCorruption(expected=stamp, actual=actual)
        if snapshot.get("version") != 1:
            raise FriendlyError(
                f"unknown serve snapshot version "
                f"{snapshot.get('version')!r} (this build reads "
                "version 1)"
            )
        if snapshot.get("model") != graph.name:
            raise FriendlyError(
                f"snapshot is for model {snapshot.get('model')!r}, "
                f"cannot restore onto {graph.name!r}"
            )
        kwargs.setdefault("cache_len", snapshot["cache_len"])
        kwargs.setdefault("pad_id", snapshot["pad_id"])
        engine = cls(graph, variables, **kwargs)
        engine._sched.tick_count = int(snapshot["tick"])
        engine._next_id = int(snapshot["next_id"])
        now = time.perf_counter()
        # active requests resume FIRST (they were running when the
        # engine died), then the queued ones in their original order —
        # appended directly, bypassing max_queue: these were already
        # admitted once, bouncing them now would turn a crash into
        # data loss
        for entry in list(snapshot["active"]) + list(snapshot["queued"]):
            req = ServeRequest(
                id=int(entry["id"]),
                prompt=np.asarray(entry["prompt"], np.int32),
                max_new_tokens=int(entry["max_new_tokens"]),
                eos_id=entry["eos_id"],
                deadline_tick=entry["deadline_tick"],
                submit_tick=int(entry["submit_tick"]),
                submit_wall=now,
                prefix=np.asarray(entry.get("emitted", ()), np.int32),
                # the failover replay keeps the ORIGINAL trace id, so
                # the re-prefill on the rebuilt engine is causally
                # linked to the pre-crash submit in the merged trace
                trace_id=str(entry.get("trace")
                             or f"t{int(entry['id'])}"),
            )
            engine._sched.queue.append(req)
            engine.metrics.record_submit()
            span = engine._tracer.span(
                "request", tick=engine.tick, id=req.id,
                trace=req.trace_id,
                prompt_len=int(req.prompt.size),
                max_new_tokens=req.max_new_tokens,
            )
            span.event("restored", tick=engine.tick,
                       prefix_len=len(req.prefix))
            engine._spans[req.id] = span
        # the restored engine's initial recovery point IS the snapshot
        # it was built from — a kill before the first periodic refresh
        # still has a complete checkpoint to fail over to
        engine._last_snapshot = snapshot
        return engine
