"""Slot-based KV-cache pool for the continuous-batching serving engine.

``models/generate.py`` preallocates one ``(B, total, hk, d)`` K/V buffer
pair per block PER CALL — correct for offline batch decode, wasteful for
serving, where requests arrive and retire continuously. The pool flips
the allocation: ONE ``(S, cache_len, hk, d)`` buffer pair per block for
the whole process (head geometry from
:func:`mmlspark_tpu.models.generate.cache_geometry`, the same fused-qkv
readout ``init_cache`` uses), where ``S`` is the number of serving slots.
A request leases a slot for its lifetime, the prefill writes its
prompt's K/V into positions ``[0, P)`` of that slot row, decode steps
append one position per tick, and retirement frees the slot for the next
request — no allocation, no reshape, no recompile anywhere in steady
state, which is what lets the scheduler's fused decode step stay a
single XLA program (the TensorFlow-style decoupled-worker dataflow,
arXiv:1605.08695, with fixed-shape device steps).

Stale K/V from a previous lease is harmless by construction: a new lease
always prefills ``[0, P)`` with ``P >= 1``, and the causal mask
(``q_offset = pos``) hides every position beyond the current request's
own write frontier.
"""

from __future__ import annotations

import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.models.generate import cache_geometry


class SlotCachePool:
    """Preallocated per-block K/V buffers with slot lease/free accounting.

    ``buffers`` is the live pytree the scheduler's jitted decode step
    reads and returns — ``{block: (K, V)}`` with each array
    ``(slots, cache_len, hk, d)`` bf16. The pool owns the host-side
    bookkeeping (which slots are leased); the arrays themselves stay on
    device and are replaced functionally each tick.
    """

    def __init__(self, graph, variables, slots: int, cache_len: int):
        if slots < 1:
            raise FriendlyError(f"slots must be >= 1, got {slots}")
        if cache_len < 2:
            raise FriendlyError(
                f"cache_len must be >= 2 (one prompt token + one "
                f"generated), got {cache_len}"
            )
        geometry = cache_geometry(graph, variables)
        if not geometry:
            raise FriendlyError(
                f"'{graph.name}' has no cache-accepting blocks; the "
                "serving engine needs the KV-cache decode path "
                "(transformer_lm family)"
            )
        self.num_slots = slots
        self.cache_len = cache_len
        self.buffers = {}
        for name, (hk, d) in geometry.items():
            # K and V must be DISTINCT arrays: the engine's decode step
            # donates the whole buffer pytree (donate_argnums), and a
            # pair aliasing one allocation cannot be donated twice
            self.buffers[name] = (
                jnp.zeros((slots, cache_len, hk, d), jnp.bfloat16),
                jnp.zeros((slots, cache_len, hk, d), jnp.bfloat16),
            )
        # LIFO free list popping the lowest id first keeps slot
        # assignment deterministic for the parity tests
        self._free = list(range(slots - 1, -1, -1))
        self._leased: set[int] = set()
        # DEVICE-resident per-slot decode state, donated through the
        # engine's fused decode-block program alongside the K/V buffers
        # (docs/SERVING.md "Decode blocks"): each slot's next write
        # position and its live flag (True = active tenant). The scanned
        # micro-steps advance these ON DEVICE between host syncs; the
        # scheduler's host bookkeeping mirrors them deterministically.
        # Free-slot convention: (pos 0, dead) — a dead row runs through
        # the fixed-shape block masked out, writing only position-0
        # garbage that the slot's next prefill overwrites.
        self.positions = jnp.zeros((slots,), jnp.int32)
        self.live = jnp.zeros((slots,), bool)

    # -- accounting --------------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def leased_count(self) -> int:
        return len(self._leased)

    @property
    def utilization(self) -> float:
        return len(self._leased) / self.num_slots

    def lease(self) -> int:
        if not self._free:
            raise FriendlyError(
                f"no free KV-cache slots (all {self.num_slots} leased); "
                "the scheduler should admit only into free slots — free "
                "a retired slot first or build the pool with more slots"
            )
        slot = self._free.pop()
        self._leased.add(slot)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._leased:
            raise FriendlyError(
                f"slot {slot} is not leased (double free, or never "
                f"leased from this pool of {self.num_slots})"
            )
        self._leased.remove(slot)
        self._free.append(slot)
        # restore the free-slot convention (pos 0, dead) so the fused
        # decode block keeps every write of this row inside the leased
        # region and its flash-decode length reads as zero
        self.positions = self.positions.at[slot].set(0)
        self.live = self.live.at[slot].set(False)

    # -- data path ---------------------------------------------------------

    def write_prefill(self, slot: int, prefill_cache: dict,
                      length: int) -> None:
        """Copy a batch-1 prefill cache (buffers of exactly ``length``
        positions, from ``init_cache(graph, variables, 1, P)``) into
        positions ``[0, length)`` of the slot's row."""
        if slot not in self._leased:
            raise FriendlyError(f"slot {slot} is not leased")
        if length > self.cache_len:
            raise FriendlyError(
                f"prefill length {length} exceeds the pool's cache_len "
                f"{self.cache_len}"
            )
        for name, (pk, pv) in self.buffers.items():
            ck, cv = prefill_cache[name]
            self.buffers[name] = (
                pk.at[slot, :length].set(ck[0, :length].astype(pk.dtype)),
                pv.at[slot, :length].set(cv[0, :length].astype(pv.dtype)),
            )
        # the slot's first decode step writes its first generated
        # token's K/V at position ``length`` (the prompt fills [0, P))
        self.positions = self.positions.at[slot].set(length)
        self.live = self.live.at[slot].set(True)
