"""Slot-based KV-cache pool for the continuous-batching serving engine.

``models/generate.py`` preallocates one ``(B, total, hk, d)`` K/V buffer
pair per block PER CALL — correct for offline batch decode, wasteful for
serving, where requests arrive and retire continuously. The pool flips
the allocation: ONE ``(S, cache_len, hk, d)`` buffer pair per block for
the whole process (head geometry from
:func:`mmlspark_tpu.models.generate.cache_geometry`, the same fused-qkv
readout ``init_cache`` uses), where ``S`` is the number of serving slots.
A request leases a slot for its lifetime, the prefill writes its
prompt's K/V into positions ``[0, P)`` of that slot row, decode steps
append one position per tick, and retirement frees the slot for the next
request — no allocation, no reshape, no recompile anywhere in steady
state, which is what lets the scheduler's fused decode step stay a
single XLA program (the TensorFlow-style decoupled-worker dataflow,
arXiv:1605.08695, with fixed-shape device steps).

Stale K/V from a previous lease is harmless by construction: a new lease
always prefills ``[0, P)`` with ``P >= 1``, and the causal mask
(``q_offset = pos``) hides every position beyond the current request's
own write frontier.

With ``mesh`` set (docs/SERVING.md "Sharded serving") the pool is the
engine's device-placement anchor: every buffer is allocated COMMITTED
to a fixed :class:`~jax.sharding.NamedSharding` — the slot dim over the
``data`` axis, the KV-head dim over the ``model`` axis when it divides
evenly — and every eager update (``write_prefill``, ``free``) is
re-committed to the same sharding before the decode block sees it.
That fixed-point is what keeps the sharded engine's jitted programs at
ONE signature-cache entry per program family: the fused block's
donated inputs and ``out_shardings``-pinned outputs present byte-for-
byte identical shardings on every tick.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.models.generate import cache_geometry
from mmlspark_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

#: headroom multiplied onto the prefill amax when fixing a slot's int8
#: quantization scale: decode steps quantize with the SAME scale
#: in-graph (a per-step rescale would invalidate already-written int8
#: rows), so the margin absorbs decode K/V drifting above the prompt's
#: range; values beyond it saturate at ±127 — graceful, and part of the
#: declared error budget (docs/PERFORMANCE.md "Quantized decode")
KV_SCALE_MARGIN = 1.5

VALID_KV_DTYPES = ("bf16", "int8")


def validate_kv_dtype(kv_dtype: str, geometry: dict) -> None:
    """Shared pool-level contract for ``kv_dtype`` (dense and paged
    pools): the flag must name a supported dtype, and int8 requires an
    even head_dim — the decode kernels' int8 VREG tile packs lanes
    pairwise and rejects odd D (the CLI surfaces this as the
    FriendlyError, not a kernel shape crash mid-serve)."""
    if kv_dtype not in VALID_KV_DTYPES:
        raise FriendlyError(
            f"kv_dtype must be one of {VALID_KV_DTYPES}, got "
            f"{kv_dtype!r}"
        )
    if kv_dtype == "int8":
        for name, (hk, d) in geometry.items():
            if d % 2:
                raise FriendlyError(
                    f"kv_dtype='int8' requires an even head_dim (the "
                    f"int8 decode-kernel tile packs lanes pairwise), "
                    f"but block '{name}' has head_dim {d}. Use "
                    f"kv_dtype='bf16' or an even d_model/heads split"
                )


def quantize_kv(values, scales):
    """Symmetric int8 quantization of K/V ``values`` (..., hk, d) with
    per-kv-head ``scales`` broadcastable over (..., hk); out-of-range
    values saturate at ±127. ONE definition shared by the pools' eager
    prefill writes and the transformer's in-graph decode-step writes,
    so both paths land bit-identical int8 for identical inputs."""
    q = jnp.round(values.astype(jnp.float32) / scales[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8)


def kv_head_scales(values, axes) -> jnp.ndarray:
    """Per-kv-head f32 quantization scales from the amax of ``values``
    over ``axes`` (every dim but the kv-head dim), with the
    ``KV_SCALE_MARGIN`` headroom and a 1.0 floor substituted for
    all-zero heads (a zero scale would divide by zero; scale 1.0 maps
    zeros to zeros exactly)."""
    amax = jnp.abs(values.astype(jnp.float32)).max(axis=axes)
    scale = amax * (KV_SCALE_MARGIN / 127.0)
    return jnp.where(scale == 0.0, 1.0, scale)


class SlotCachePool:
    """Preallocated per-block K/V buffers with slot lease/free accounting.

    ``buffers`` is the live pytree the scheduler's jitted decode step
    reads and returns — ``{block: (K, V)}`` with each array
    ``(slots, cache_len, hk, d)`` bf16. The pool owns the host-side
    bookkeeping (which slots are leased); the arrays themselves stay on
    device and are replaced functionally each tick.

    ``kv_dtype="int8"`` (docs/PERFORMANCE.md "Quantized decode") stores
    K/V as int8 — HALF the bf16 pool's HBM bytes — and each block's
    entry grows to ``(K, V, k_scale, v_scale)`` with (slots, hk) f32
    per-(slot, kv-head) scales as extra cache-pytree leaves: prefill
    fixes a slot's scales from its prompt amax (+ headroom), decode
    steps quantize in-graph against them, and the flash-decode kernel
    dequantizes in-VMEM. All four leaves are DISTINCT arrays (donation)
    and all four carry pinned shardings under a mesh. The bf16 mode is
    unchanged — it remains the accuracy oracle the int8 parity suite
    measures against.
    """

    def __init__(self, graph, variables, slots: int, cache_len: int, *,
                 mesh=None, kv_dtype: str = "bf16"):
        if slots < 1:
            raise FriendlyError(f"slots must be >= 1, got {slots}")
        if cache_len < 2:
            raise FriendlyError(
                f"cache_len must be >= 2 (one prompt token + one "
                f"generated), got {cache_len}"
            )
        geometry = cache_geometry(graph, variables)
        if not geometry:
            raise FriendlyError(
                f"'{graph.name}' has no cache-accepting blocks; the "
                "serving engine needs the KV-cache decode path "
                "(transformer_lm family)"
            )
        self.mesh = mesh
        if mesh is not None:
            data = int(mesh.shape.get(DATA_AXIS, 1))
            if slots % data:
                raise FriendlyError(
                    f"slots ({slots}) must be a multiple of the mesh's "
                    f"'{DATA_AXIS}' axis ({data}): each device in the "
                    "data axis holds slots/data whole slot rows of "
                    "every K/V buffer. Round slots up (free slots are "
                    "natural pad rows — dead on device, zero decode "
                    "cost beyond the fixed shapes) or shrink the axis"
                )
        validate_kv_dtype(kv_dtype, geometry)
        self.kv_dtype = kv_dtype
        self.num_slots = slots
        self.cache_len = cache_len
        quantized = kv_dtype == "int8"
        store_dtype = jnp.int8 if quantized else jnp.bfloat16
        # device-placement anchors under a mesh; None on a single device
        self._slot_sharding = self._kv_shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._slot_sharding = NamedSharding(mesh, P(DATA_AXIS))
            msize = int(mesh.shape.get(MODEL_AXIS, 1))
            self._kv_shardings = {}
            for name, (hk, d) in geometry.items():
                # shard KV heads over the model axis only when they tile
                # evenly (GQA/MQA models with hk < model size replicate
                # the head dim, mirroring build_param_shardings' degrade)
                head = (
                    MODEL_AXIS if msize > 1 and hk % msize == 0 else None
                )
                sh = NamedSharding(mesh, P(DATA_AXIS, None, head, None))
                if quantized:
                    # (slots, hk) scale leaves shard exactly like the
                    # dims they index: slots over data, heads over model
                    ssc = NamedSharding(mesh, P(DATA_AXIS, head))
                    self._kv_shardings[name] = (sh, sh, ssc, ssc)
                else:
                    self._kv_shardings[name] = (sh, sh)
        self.buffers = {}
        for name, (hk, d) in geometry.items():
            # K and V must be DISTINCT arrays: the engine's decode step
            # donates the whole buffer pytree (donate_argnums), and a
            # pair aliasing one allocation cannot be donated twice —
            # same for the int8 mode's two scale leaves
            k = jnp.zeros((slots, cache_len, hk, d), store_dtype)
            v = jnp.zeros((slots, cache_len, hk, d), store_dtype)
            entry = (k, v)
            if quantized:
                entry = (
                    k, v,
                    jnp.ones((slots, hk), jnp.float32),
                    jnp.ones((slots, hk), jnp.float32),
                )
            if self._kv_shardings is not None:
                entry = tuple(jax.device_put(
                    entry, self._kv_shardings[name]
                ))
            self.buffers[name] = entry
        # LIFO free list popping the lowest id first keeps slot
        # assignment deterministic for the parity tests
        self._free = list(range(slots - 1, -1, -1))
        self._leased: set[int] = set()
        # deferred-free window (docs/SERVING.md "Async host loop"):
        # while the engine has a decode block IN FLIGHT that was
        # dispatched seeing this slot live, returning the slot to the
        # free list immediately would let the next admission re-lease
        # it and the in-flight block's masked writes would land in the
        # NEW tenant's row. The engine brackets each in-flight window
        # with defer_frees(gen)/flush_frees(gen): frees issued inside
        # the window reset the device row state immediately (those
        # updates are dependency-ordered AFTER the in-flight block's
        # outputs) but the free-list return waits until the stamped
        # generation's block has been fetched.
        self._defer_gen: int | None = None
        self._deferred: list[tuple[int, int]] = []
        self._deferred_slots: set[int] = set()
        # DEVICE-resident per-slot decode state, donated through the
        # engine's fused decode-block program alongside the K/V buffers
        # (docs/SERVING.md "Decode blocks"): each slot's next write
        # position and its live flag (True = active tenant). The scanned
        # micro-steps advance these ON DEVICE between host syncs; the
        # scheduler's host bookkeeping mirrors them deterministically.
        # Free-slot convention: (pos 0, dead) — a dead row runs through
        # the fixed-shape block masked out, writing only position-0
        # garbage that the slot's next prefill overwrites.
        self.positions = self._commit_slot(jnp.zeros((slots,), jnp.int32))
        self.live = self._commit_slot(jnp.zeros((slots,), bool))

    # -- sharding anchors --------------------------------------------------

    def _commit_slot(self, arr):
        """Commit an (S,)-shaped per-slot array to the data axis (no-op
        without a mesh)."""
        if self._slot_sharding is None:
            return arr
        return jax.device_put(arr, self._slot_sharding)

    @property
    def kv_shardings(self):
        """``{block: (NamedSharding, NamedSharding)}`` matching
        ``buffers`` — what the engine pins the decode block's
        ``out_shardings`` to — or None without a mesh."""
        return self._kv_shardings

    @property
    def slot_sharding(self):
        """NamedSharding of the per-slot (S,) state (data axis), or
        None without a mesh."""
        return self._slot_sharding

    # -- accounting --------------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def leased_count(self) -> int:
        return len(self._leased)

    def leased_slots(self) -> list[int]:
        """Leased slot ids, ascending — what the engine's kill-parking
        walks to return every held slot deterministically."""
        return sorted(self._leased)

    @property
    def utilization(self) -> float:
        return len(self._leased) / self.num_slots

    def lease(self) -> int:
        if not self._free:
            raise FriendlyError(
                f"no free KV-cache slots (all {self.num_slots} leased); "
                "the scheduler should admit only into free slots — free "
                "a retired slot first or build the pool with more slots"
            )
        slot = self._free.pop()
        self._leased.add(slot)
        return slot

    def defer_frees(self, gen: int) -> None:
        """Open (or advance) a deferred-free window: until
        :meth:`flush_frees` passes ``gen``, freed slots reset their
        device row state immediately but stay OFF the free list — no
        new lease can collide with a decode block dispatched before
        the free (the async engine's zombie-row protection)."""
        self._defer_gen = gen

    def flush_frees(self, completed_gen: int | None = None) -> None:
        """Return every deferred slot whose stamped dispatch generation
        is ``<= completed_gen`` (all of them when None) to the free
        list, and close the window when None."""
        if completed_gen is None:
            self._defer_gen = None
        keep = []
        for gen, slot in self._deferred:
            if completed_gen is None or gen <= completed_gen:
                self._deferred_slots.discard(slot)
                self._leased.discard(slot)
                self._free.append(slot)
            else:
                keep.append((gen, slot))
        self._deferred = keep

    def free(self, slot: int) -> None:
        if slot not in self._leased or slot in self._deferred_slots:
            raise FriendlyError(
                f"slot {slot} is not leased (double free, or never "
                f"leased from this pool of {self.num_slots})"
            )
        if self._defer_gen is not None:
            self._deferred.append((self._defer_gen, slot))
            self._deferred_slots.add(slot)
        else:
            self._leased.remove(slot)
            self._free.append(slot)
        # restore the free-slot convention (pos 0, dead) so the fused
        # decode block keeps every write of this row inside the leased
        # region and its flash-decode length reads as zero
        self._commit_slot_pair(
            self.positions.at[slot].set(0),
            self.live.at[slot].set(False),
        )
        if self.kv_dtype == "int8":
            # release the slot's quantization-scale state back to the
            # 1.0 init: a freed (quarantined/preempted/retired) lease
            # must not leak its calibration into the next tenant, and
            # the parity tests assert the reset
            new_buffers = {}
            for name, (k, v, ks, vs) in self.buffers.items():
                new_buffers[name] = (
                    k, v, ks.at[slot].set(1.0), vs.at[slot].set(1.0),
                )
            if self._kv_shardings is not None:
                new_buffers = jax.device_put(
                    new_buffers, self._kv_shardings
                )
            self.buffers = new_buffers

    def _commit_slot_pair(self, positions, live) -> None:
        """Rebind positions+live behind ONE pinned update — committing
        them separately would issue two eager dispatches per
        retire/admit, and the retire path runs once per finished
        request."""
        if self._slot_sharding is not None:
            positions, live = jax.device_put(
                (positions, live),
                (self._slot_sharding, self._slot_sharding),
            )
        self.positions, self.live = positions, live

    # -- data path ---------------------------------------------------------

    def write_prefill(self, slot: int, prefill_cache: dict,
                      length: int, start: int = 0) -> None:
        """Copy a batch-1 prefill cache (buffers holding valid K/V for
        positions ``[0, length)``) into positions ``[start, length)``
        of the slot's row — ``start=0`` is the classic full prefill;
        ``start>0`` resumes a partial fill whose prefix ``[0, start)``
        the slot already holds (same contract as the paged pool's
        ``write_prefill``, which prefix-cache resume uses)."""
        if slot not in self._leased:
            raise FriendlyError(f"slot {slot} is not leased")
        if length > self.cache_len:
            raise FriendlyError(
                f"prefill length {length} exceeds the pool's cache_len "
                f"{self.cache_len}"
            )
        if not 0 <= start < max(length, 1):
            raise FriendlyError(
                f"write_prefill start {start} must lie in [0, length "
                f"{length})"
            )
        quantized = self.kv_dtype == "int8"
        if quantized and start:
            # a lease's int8 scales are FIXED from its whole-prompt
            # amax before the first decode dispatch; a partial write
            # cannot re-derive them without dequantizing the resident
            # prefix, so the dense pool requires full writes
            raise FriendlyError(
                "dense int8 pools require start=0 writes: quantization "
                "scales are fixed per lease from the whole prompt "
                "(use the paged pool for resumable int8 fills)"
            )
        new_buffers = {}
        for name, entry in self.buffers.items():
            ck, cv = prefill_cache[name]
            if quantized:
                pk, pv, pks, pvs = entry
                # the prompt amax (+ margin) FIXES this lease's scales:
                # decode steps quantize against them in-graph, so they
                # must be set before the first block dispatch
                ck0, cv0 = ck[0, :length], cv[0, :length]
                k_scl = kv_head_scales(ck0, axes=(0, 2))  # (hk,)
                v_scl = kv_head_scales(cv0, axes=(0, 2))
                nk = pk.at[slot, :length].set(quantize_kv(ck0, k_scl))
                nv = pv.at[slot, :length].set(quantize_kv(cv0, v_scl))
                new_buffers[name] = (
                    nk, nv,
                    pks.at[slot].set(k_scl), pvs.at[slot].set(v_scl),
                )
            else:
                pk, pv = entry
                nk = pk.at[slot, start:length].set(
                    ck[0, start:length].astype(pk.dtype)
                )
                nv = pv.at[slot, start:length].set(
                    cv[0, start:length].astype(pv.dtype)
                )
                new_buffers[name] = (nk, nv)
        if self._kv_shardings is not None:
            # the eager scatters' output shardings are whatever GSPMD
            # propagated from mixing the pool rows with the prefill
            # cache — re-commit to the pool's canonical shardings so
            # the decode block's donated inputs never change signature
            # (the compile-count pins depend on it). ONE device_put of
            # the whole pytree, not one per K/V per block: the admit
            # path runs this once per joiner.
            new_buffers = jax.device_put(new_buffers, self._kv_shardings)
        self.buffers = new_buffers
        # the slot's first decode step writes its first generated
        # token's K/V at position ``length`` (the prompt fills [0, P))
        self._commit_slot_pair(
            self.positions.at[slot].set(length),
            self.live.at[slot].set(True),
        )

    # -- accounting for telemetry ------------------------------------------

    def device_bytes_per_device(self) -> int:
        """KV-pool bytes resident PER DEVICE: each array's local shard
        size (``sharding.shard_shape``) times its itemsize, summed over
        every K/V buffer plus the per-slot position/live state. On a
        single device this is simply the pool's total footprint; under
        a mesh it is what each chip's HBM actually holds — the figure
        ``ServeMetrics.snapshot()`` reports as
        ``cache_pool_bytes_per_device``."""
        total = 0
        arrays = [a for pair in self.buffers.values() for a in pair]
        arrays += [self.positions, self.live]
        for arr in arrays:
            shard = arr.sharding.shard_shape(arr.shape)
            total += math.prod(shard) * arr.dtype.itemsize
        return int(total)
