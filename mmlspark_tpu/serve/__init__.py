"""Continuous-batching serving engine over a slot-based KV-cache pool.

The subsystem that turns ``models/generate.py``'s per-call static-shape
decode into a multi-tenant engine (docs/SERVING.md): a preallocated
``(slots, cache_len, hk, d)`` K/V pool (:mod:`cache_pool`), a
tick-based continuous-batching scheduler (:mod:`scheduler`), the public
``ServeEngine.submit/step/run`` API with admission control and
per-request deadlines (:mod:`engine`), serving observability as
``MetricData`` records (:mod:`metrics`), and a synthetic-traffic demo
(:mod:`demo`, the ``python -m mmlspark_tpu serve`` body).

The engine is fault-tolerant (docs/SERVING.md "Failure semantics"):
transient dispatch errors retry, ``RESOURCE_EXHAUSTED`` degrades
gracefully, poisoned/undispatachable requests quarantine with terminal
status ``"failed"`` instead of killing ``run()``, and
``ServeEngine.snapshot()``/``restore()`` checkpoint host-side request
state for crash recovery. :class:`~mmlspark_tpu.core.faults.FaultInjector`
(re-exported here) is the deterministic harness that proves all of it.

For replicated serving, :class:`~mmlspark_tpu.serve.supervisor.ReplicaSet`
(docs/SERVING.md "Replicated serving") puts N engines behind one
``submit()/run()`` facade with health probes, snapshot-based failover,
hedged routing, and zero-loss drain.

For DISAGGREGATED serving, :class:`~mmlspark_tpu.serve.fleet.DisaggFleet`
(docs/SERVING.md "Disaggregated fleet") splits the replicas into
dedicated prefill and decode roles behind the same facade: prefill
replicas ship each request's KV + first token to decode replicas over
a cross-replica hand-off plane (the ``serve.handoff`` fault site), a
fleet-wide prefix index makes any replica's completed prefill every
replica's cache hit, and an :class:`~mmlspark_tpu.serve.fleet.AutoscalePolicy`
grows/shrinks each role elastically from a parked device budget.

For MULTI-MODEL serving, :class:`~mmlspark_tpu.serve.multimodel.
MultiModelEngine` (docs/SERVING.md "Multi-model serving") hosts several
named deployments — stateful LM-decode engines next to stateless
power-of-two-bucketed batch deployments over any non-causal
``build_model`` graph (ONNX-imported included) — behind one
``submit(model=...)`` facade with per-model admission/SLOs/telemetry
namespaces, a round-robin device budget, and the ``serve.batch`` fault
site covering stateless dispatches.
"""

from mmlspark_tpu.core.faults import (  # noqa: F401
    Fault,
    FaultInjector,
    parse_fault_spec,
)
from mmlspark_tpu.core.perf import (  # noqa: F401
    PerfAnalytics,
    SloMonitor,
    SloTargets,
    export_chrome_trace,
    parse_slo_spec,
)
from mmlspark_tpu.serve.cache_pool import SlotCachePool  # noqa: F401
from mmlspark_tpu.serve.engine import ServeEngine  # noqa: F401
from mmlspark_tpu.serve.fleet import (  # noqa: F401
    AutoscalePolicy,
    DisaggFleet,
    parse_autoscale_spec,
)
from mmlspark_tpu.serve.metrics import ServeMetrics  # noqa: F401
from mmlspark_tpu.serve.multimodel import (  # noqa: F401
    BatchDeployment,
    BatchResult,
    MultiModelEngine,
    engine_from_spec,
    parse_models_spec,
)
from mmlspark_tpu.serve.scheduler import (  # noqa: F401
    ContinuousBatchScheduler,
    RequestResult,
    ServeRequest,
)
from mmlspark_tpu.serve.supervisor import ReplicaSet  # noqa: F401
