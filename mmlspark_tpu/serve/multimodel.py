"""Multi-model serving: one engine, many graphs (docs/SERVING.md
"Multi-model serving").

The reference's core serving surface is batched inference over
*arbitrary loaded models* (``CNTKModel.transform``, ``ImageFeaturizer``
over zoo-downloaded graphs); :class:`MultiModelEngine` closes that gap
for this stack. One engine hosts several NAMED deployments behind a
single ``submit(model=...)/step()/run()`` facade:

- **LM deployments** — the existing :class:`~mmlspark_tpu.serve.engine.
  ServeEngine` slot/KV/fused-decode-block machinery, UNCHANGED: same
  compile-count pins (``num_decode_blocks`` / ``num_prefill_buckets``),
  same one-host-sync-per-block property, token streams bit-identical to
  a dedicated single-model engine.
- **Stateless batch deployments** (:class:`BatchDeployment`) — any
  non-causal graph from ``build_model`` (ResNet / BiLSTM / MLP /
  ONNX-imported), executed as power-of-two-BUCKETED, donated,
  one-program-per-bucket batch dispatches. The batch-size ladder reuses
  the prefill-bucket idiom: ``k`` queued examples pad to the next power
  of two (capped at ``max_batch``), so the dispatch program count is
  O(log max_batch) — ``num_batch_buckets`` — however traffic arrives,
  and padding rows are sliced off before results surface (a bucket-size
  batch pads nothing, so its output is bit-equal to a direct
  ``graph.apply`` on the same batch).

Cross-cutting planes, shared with the single-model engine:

- **Per-model admission + SLOs** — each deployment keeps its own queue,
  :class:`~mmlspark_tpu.core.perf.SloTargets` monitor, and shed signal;
  one model burning its SLO sheds ONLY its own admissions.
- **One device budget** — ``step()`` round-robins at most
  ``device_budget`` deployment dispatches per engine tick (None = every
  deployment with work), so a saturating LM stream cannot starve
  classifier batches: any deployment with queued work dispatches within
  ``ceil(deployments / device_budget)`` ticks.
- **Telemetry namespaces** — all deployments share ONE
  :class:`~mmlspark_tpu.core.telemetry.MetricRegistry`; each writes
  through a :class:`~mmlspark_tpu.core.telemetry.NamespacedRegistry`
  view with prefix ``model{name}.``, so per-model TTFT / throughput /
  SLO metrics surface as ``model{name}.serve.*`` in one collision-free
  Prometheus exposition and in ``metrics_dict()["registry"]``.
- **Fault envelope** — the ``serve.batch`` fault site (core/faults.py)
  fires before every stateless dispatch: transients retry behind the
  same capped deterministic backoff as LM decode, ``oom`` halves the
  deployment's batch admission cap (down the EXISTING bucket ladder —
  no new programs) and recovers after clean dispatches, and retry
  exhaustion quarantines the batch as ``"failed"``.

ONNX ingestion is a first-class registration path:
:meth:`MultiModelEngine.add_onnx` (or ``arch "onnx"`` with ``path=`` in
the ``--models`` CLI spec) imports a foreign graph via
``models/onnx_import.py`` and serves it as a batch deployment — the
imported initializers ARE the variables.
"""

from __future__ import annotations

import dataclasses
import difflib
import time
import warnings
from collections import deque

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.faults import (
    FaultInjector,
    EngineKilled,
    is_resource_exhausted,
    is_transient,
)
from mmlspark_tpu.core.perf import SloMonitor, SloTargets, parse_slo_spec
from mmlspark_tpu.core.telemetry import (
    FlightRecorder,
    MetricRegistry,
    NamespacedRegistry,
    RetraceWatchdog,
)
from mmlspark_tpu.serve.engine import ServeEngine
from mmlspark_tpu.serve.metrics import ServeMetrics


@dataclasses.dataclass
class BatchResult:
    """Terminal record for one stateless batch request: ``status`` is
    ``"completed"`` (``output`` carries the example's result row) or
    ``"failed"`` (quarantined by fault handling; ``output`` is None).
    ``generated`` is always 1 — one example in, one result out — so the
    shared metrics plane's tokens/sec reads as examples/sec for batch
    deployments."""

    id: int
    status: str
    output: np.ndarray | None
    submit_tick: int
    finish_tick: int
    wall_s: float
    generated: int = 1


@dataclasses.dataclass
class _BatchReq:
    """One queued example. ``submit_tick``/``submit_wall`` are the
    fields :meth:`ServeMetrics.record_first_token` reads, so batch TTFT
    rides the same histogram as LM TTFT."""

    id: int
    x: np.ndarray
    submit_tick: int
    submit_wall: float


class BatchDeployment:
    """Stateless batched inference over one non-causal graph.

    The batch-size analog of the LM engine's bucketed prefill: each
    tick drains up to ``min(queue, admission cap, max_batch)`` examples,
    pads the stacked batch to the next power of two on the ladder
    {1, 2, ..., max_batch}, and runs ONE donated jitted dispatch —
    at most :attr:`num_batch_buckets` XLA programs ever compile,
    however traffic arrives (pinned by ``RetraceWatchdog`` +
    ``ProgramCountingJit``, same counting contract as the LM pins).
    One host sync per dispatch fetches the whole output batch.
    """

    kind = "batch"

    def __init__(self, graph, variables, *, max_batch: int = 8,
                 max_queue: int = 64,
                 slo=None,
                 faults: FaultInjector | None = None,
                 retry_limit: int = 2,
                 retry_backoff_s: float = 0.0,
                 degrade_recover_ticks: int = 8,
                 recorder: FlightRecorder | None = None,
                 registry=None):
        if graph.extra.get("causal", False):
            raise FriendlyError(
                f"'{graph.name}' is a causal LM; serve it as an LM "
                "deployment (MultiModelEngine.add_lm) — batch "
                "deployments run stateless non-causal graphs only"
            )
        if max_batch < 1:
            raise FriendlyError(
                f"max_batch must be >= 1, got {max_batch}"
            )
        if retry_limit < 0:
            raise FriendlyError(
                f"retry_limit must be >= 0, got {retry_limit}"
            )
        self.graph = graph
        self.variables = variables
        # floor to a power of two: batch buckets live on the ladder
        # {1, 2, 4, ..., max_batch}, so the dispatch program count is
        # O(log) — exactly the decode_block flooring rule
        self.max_batch = 1 << (int(max_batch).bit_length() - 1)
        self.max_queue = max_queue
        self.recorder = (
            recorder if recorder is not None else FlightRecorder()
        )
        self.metrics = ServeMetrics(
            graph.name, self.max_batch, registry=registry,
        )
        self._faults = faults
        self._retry_limit = retry_limit
        self._retry_backoff_s = retry_backoff_s
        self._degrade_recover_ticks = max(1, degrade_recover_ticks)
        #: memory-pressure degradation state: the current batch
        #: admission cap (walks DOWN the existing bucket ladder on OOM,
        #: re-escalates after ``degrade_recover_ticks`` clean
        #: dispatches — never a new program)
        self._admit_cap = self.max_batch
        self._ok_dispatches = 0
        if isinstance(slo, str):
            slo = parse_slo_spec(slo)
        if isinstance(slo, SloTargets):
            slo = SloMonitor(slo, recorder=self.recorder,
                             registry=self.metrics.registry)
        self._slo: SloMonitor | None = slo
        if slo is not None:
            self.metrics.attach_slo(slo)
        if faults is not None and faults.listener is None:
            def _on_fault(kind: str, site: str) -> None:
                self.metrics.record_fault(kind)
                self.recorder.record(
                    "fault_injected", tick=self.tick, kind=kind,
                    site=site,
                )
            faults.listener = _on_fault
        self._queue: deque[_BatchReq] = deque()
        self._next_id = 0
        self._tick = 0
        self._dead = False
        #: example shape/dtype, locked by the first submit — every
        #: later example must match (one program family per bucket
        #: REQUIRES homogeneous examples)
        self._example_shape: tuple | None = None
        self._example_dtype = None

        import jax

        def _apply(variables, x):
            return graph.apply(variables, x)

        # the batch input is donated (it is rebuilt per dispatch);
        # variables are NOT — they serve every future dispatch
        self._dispatch = RetraceWatchdog(
            _program_counting(jax.jit(_apply, donate_argnums=(1,))),
            f"serve.batch.{graph.name}",
            registry=self.metrics.registry,
            recorder=self.recorder,
            expected_programs=self.num_batch_buckets,
        )

    # -- bucket ladder ------------------------------------------------------

    def batch_bucket(self, k: int) -> int:
        """Padded batch size the dispatch program runs at for ``k``
        queued examples: the next power of two >= max(k, 1), capped at
        ``max_batch`` (the admit loop guarantees k <= max_batch)."""
        bucket = 1
        while bucket < k:
            bucket *= 2
        return min(bucket, self.max_batch)

    @property
    def num_batch_buckets(self) -> int:
        """How many distinct dispatch programs CAN exist for this
        deployment — one per ladder bucket, the ceiling the
        compile-guard tests pin stateless dispatch to."""
        return len({
            self.batch_bucket(k) for k in range(1, self.max_batch + 1)
        })

    @property
    def batch_compile_count(self) -> int:
        """How many DISTINCT XLA programs the batch dispatch has
        compiled — bounded by ``num_batch_buckets`` for the life of the
        deployment (asserted in tests via the same ``jit_cache_size``
        contract as the LM pins)."""
        from mmlspark_tpu.testing.compile_guard import jit_cache_size

        return jit_cache_size(self._dispatch)

    # -- introspection ------------------------------------------------------

    @property
    def tick(self) -> int:
        return self._tick

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def busy(self) -> bool:
        return bool(self._queue)

    @property
    def degraded(self) -> bool:
        return self._admit_cap < self.max_batch

    # -- fault handling -----------------------------------------------------

    def _backoff(self, attempts: int) -> None:
        self.metrics.record_retry()
        self.recorder.record("retry", tick=self._tick, attempt=attempts)
        if self._retry_backoff_s > 0:
            time.sleep(self._retry_backoff_s * attempts)

    def _note_oom(self, tick: int) -> None:
        """Graceful degradation on RESOURCE_EXHAUSTED: halve the batch
        admission cap — the smaller batch lands on an EXISTING ladder
        bucket, so degradation never compiles a new program. The queued
        examples are requeued untouched and redispatch next tick."""
        self._admit_cap = max(1, self._admit_cap // 2)
        self._ok_dispatches = 0
        self.metrics.set_degraded(True)
        self.recorder.record(
            "degraded", tick=tick, site="serve.batch",
            admit_cap=self._admit_cap,
        )

    def _note_clean_dispatch(self, tick: int) -> None:
        if not self.degraded:
            return
        self._ok_dispatches += 1
        if self._ok_dispatches < self._degrade_recover_ticks:
            return
        self._ok_dispatches = 0
        self._admit_cap = min(self.max_batch, self._admit_cap * 2)
        self.metrics.set_degraded(self.degraded)
        self.recorder.record(
            "recovered" if not self.degraded else "re_escalated",
            tick=tick, admit_cap=self._admit_cap,
        )

    # -- public API ---------------------------------------------------------

    def submit(self, x) -> int:
        """Queue ONE example (no batch dim — batching is the
        deployment's job); returns its id. The first submit locks the
        deployment's example shape/dtype; mismatches and a full queue
        raise :class:`FriendlyError` (admission control)."""
        x = np.asarray(x)
        if self._example_shape is None:
            self._example_shape = tuple(x.shape)
            self._example_dtype = x.dtype
        elif (tuple(x.shape) != self._example_shape
                or x.dtype != self._example_dtype):
            raise FriendlyError(
                f"example shape/dtype {tuple(x.shape)}/{x.dtype} does "
                f"not match this deployment's locked "
                f"{self._example_shape}/{self._example_dtype} "
                f"(model '{self.graph.name}'); one bucket ladder "
                "serves ONE example geometry — submit matching "
                "examples or add a second deployment"
            )
        if len(self._queue) >= self.max_queue:
            self.metrics.record_reject()
            self.recorder.record(
                "rejected", tick=self._tick, reason="queue_full",
            )
            raise FriendlyError(
                f"deployment '{self.graph.name}' queue is full "
                f"({self.max_queue}); retry later or raise max_queue"
            )
        req = _BatchReq(
            id=self._next_id, x=x, submit_tick=self._tick,
            submit_wall=time.perf_counter(),
        )
        self._next_id += 1
        self._queue.append(req)
        self.metrics.record_submit()
        return req.id

    def step(self) -> list[BatchResult]:
        """One deployment tick: drain up to ``min(queue, admission cap,
        max_batch)`` examples, pad to the ladder bucket, fire the
        ``serve.batch`` fault hook, run ONE donated dispatch, slice the
        padding rows off, retire every example in the batch. One host
        sync per dispatch."""
        if self._dead:
            raise FriendlyError(
                f"deployment '{self.graph.name}' was killed "
                "(EngineKilled); rebuild the engine instead of "
                "stepping it again"
            )
        t0 = time.perf_counter()
        tick = self._tick
        self._tick += 1
        if not self._queue:
            self.metrics.sample_tick(0, 0, time.perf_counter() - t0, 0)
            return []
        if self._slo is not None:
            self._slo.evaluate(tick=tick)
            if self._slo.should_shed:
                # shed = suppress NEW dispatches; queued examples wait
                # (they are admission-queued, not in flight)
                self.metrics.record_slo_shed()
                self.metrics.sample_tick(
                    len(self._queue), 0, time.perf_counter() - t0, 0,
                )
                return []
        k = min(len(self._queue), self._admit_cap, self.max_batch)
        batch = [self._queue.popleft() for _ in range(k)]
        bucket = self.batch_bucket(k)
        x = np.stack([r.x for r in batch])
        if bucket > k:
            pad = np.zeros((bucket - k,) + x.shape[1:], x.dtype)
            x = np.concatenate([x, pad])
        attempts = 0
        d0 = time.perf_counter()
        while True:
            try:
                if self._faults is not None:
                    # BEFORE the dispatch, so a raised fault never
                    # consumes the donated batch buffer
                    self._faults.fire(
                        "serve.batch", tick=tick, request=batch[0].id,
                    )
                with warnings.catch_warnings():
                    # XLA warns when a donated input buffer finds no
                    # same-shaped output to alias (e.g. a classifier
                    # whose logits are narrower than its features) —
                    # expected here, the donation is best-effort
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable",
                    )
                    out = np.asarray(self._dispatch(self.variables, x))
                break
            except EngineKilled:
                self._dead = True
                for r in reversed(batch):
                    self._queue.appendleft(r)
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                if is_resource_exhausted(e):
                    self._note_oom(tick)
                    for r in reversed(batch):
                        self._queue.appendleft(r)
                    self.metrics.sample_tick(
                        len(self._queue), 0,
                        time.perf_counter() - t0, 0,
                    )
                    return []
                if not is_transient(e):
                    raise
                if attempts < self._retry_limit:
                    attempts += 1
                    self._backoff(attempts)
                    continue
                # retry exhaustion: quarantine the WHOLE batch as
                # "failed" — the deployment keeps serving
                results = []
                for r in batch:
                    self.metrics.record_quarantine()
                    self.recorder.record(
                        "quarantine", tick=tick, id=r.id,
                        reason="retry_exhausted",
                    )
                    res = BatchResult(
                        id=r.id, status="failed", output=None,
                        submit_tick=r.submit_tick, finish_tick=tick,
                        wall_s=time.perf_counter() - r.submit_wall,
                        generated=0,
                    )
                    self.metrics.record_finish(res)
                    results.append(res)
                self.metrics.sample_tick(
                    len(self._queue), 0, time.perf_counter() - t0, 0,
                )
                return results
        dispatch_s = time.perf_counter() - d0
        self._note_clean_dispatch(tick)
        # per-"token" here means per-EXAMPLE: k results in dispatch_s
        self.metrics.record_decode(
            k, dispatch_s, tokens_emitted=k, block=bucket,
        )
        self.recorder.record(
            "batch_dispatch", tick=tick, model=self.graph.name,
            size=k, bucket=bucket,
        )
        results = []
        for i, r in enumerate(batch):
            self.metrics.record_first_token(r, tick, bucket=bucket)
            res = BatchResult(
                id=r.id, status="completed", output=out[i],
                submit_tick=r.submit_tick, finish_tick=tick,
                wall_s=time.perf_counter() - r.submit_wall,
            )
            self.metrics.record_finish(res)
            results.append(res)
        self.metrics.sample_tick(
            len(self._queue), k, time.perf_counter() - t0, k,
        )
        return results


def _program_counting(jitted):
    """Wrap a jitted callable in the sharding-robust XLA-program
    counter the LM engine pins with (lazy import: this module must stay
    importable without dragging the testing helpers in eagerly)."""
    from mmlspark_tpu.testing.compile_guard import ProgramCountingJit

    return ProgramCountingJit(jitted)


class MultiModelEngine:
    """Several named model deployments behind one submit/step/run
    facade, interleaved under one device budget.

    ``device_budget`` caps deployment dispatches per engine tick
    (None = every deployment with work each tick); a round-robin cursor
    over the registration order guarantees no deployment starves: with
    D busy deployments and budget B, every one dispatches at least once
    per ``ceil(D / B)`` ticks. ``faults`` / ``recorder`` / ``registry``
    are SHARED across deployments — one fault timeline, one telemetry
    registry with per-model ``model{name}.`` namespaces.
    """

    def __init__(self, *, device_budget: int | None = None,
                 recorder: FlightRecorder | None = None,
                 faults: FaultInjector | None = None,
                 registry: MetricRegistry | None = None):
        if device_budget is not None and device_budget < 1:
            raise FriendlyError(
                f"device_budget must be >= 1, got {device_budget}"
            )
        self.device_budget = device_budget
        self.registry = (
            registry if registry is not None else MetricRegistry()
        )
        self.recorder = (
            recorder if recorder is not None else FlightRecorder()
        )
        self._faults = faults
        # claim the shared injector's listener BEFORE deployments can
        # (a deployment only claims it when unset): fault events from
        # every model land in ONE control-plane timeline
        if faults is not None and faults.listener is None:
            self._m_faults = self.registry.counter(
                "multimodel.faults_injected"
            )

            def _on_fault(kind: str, site: str) -> None:
                self._m_faults.inc()
                self.recorder.record(
                    "fault_injected", tick=self._tick, kind=kind,
                    site=site,
                )
            faults.listener = _on_fault
        self._deployments: dict[str, ServeEngine | BatchDeployment] = {}
        self._order: list[str] = []
        self._rr = 0
        self._tick = 0
        self._next_gid = 0
        #: (model, deployment-local id) -> global id, popped at finish
        self._gid: dict[tuple[str, int], int] = {}
        #: global id -> model name, kept after finish (model_of)
        self._model_of: dict[int, str] = {}

    # -- registration -------------------------------------------------------

    def _check_name(self, name: str) -> None:
        if not name or any(c in name for c in ".;:= "):
            raise FriendlyError(
                f"deployment name {name!r} is invalid: names feed the "
                "model{name}.serve.* metric namespace and the CLI spec "
                "grammar, so they must be non-empty and free of "
                "'.', ';', ':', '=' and spaces"
            )
        if name in self._deployments:
            raise FriendlyError(
                f"deployment '{name}' already exists; names are unique "
                "per engine"
            )

    def _view(self, name: str) -> NamespacedRegistry:
        return NamespacedRegistry(self.registry, f"model{name}.")

    def add_lm(self, name: str, graph, variables,
               **engine_kwargs) -> ServeEngine:
        """Register a stateful LM-decode deployment: a full
        :class:`ServeEngine` (slots / KV pool / fused decode blocks /
        bucketed prefill, unchanged compile pins) writing its metrics
        through the shared registry under ``model{name}.``."""
        self._check_name(name)
        for key in ("faults", "recorder", "registry", "replica"):
            if key in engine_kwargs:
                raise FriendlyError(
                    f"'{key}' is managed by MultiModelEngine — pass it "
                    "to the MultiModelEngine constructor, not through "
                    "deployment kwargs"
                )
        eng = ServeEngine(
            graph, variables, faults=self._faults,
            recorder=self.recorder, registry=self._view(name),
            **engine_kwargs,
        )
        self._deployments[name] = eng
        self._order.append(name)
        self.recorder.record(
            "deployment_added", tick=self._tick, model=name, kind="lm",
            arch=graph.name,
        )
        return eng

    def add_batch(self, name: str, graph, variables,
                  **deploy_kwargs) -> BatchDeployment:
        """Register a stateless batch deployment for a non-causal
        graph."""
        self._check_name(name)
        for key in ("faults", "recorder", "registry"):
            if key in deploy_kwargs:
                raise FriendlyError(
                    f"'{key}' is managed by MultiModelEngine — pass it "
                    "to the MultiModelEngine constructor, not through "
                    "deployment kwargs"
                )
        dep = BatchDeployment(
            graph, variables, faults=self._faults,
            recorder=self.recorder, registry=self._view(name),
            **deploy_kwargs,
        )
        self._deployments[name] = dep
        self._order.append(name)
        self.recorder.record(
            "deployment_added", tick=self._tick, model=name,
            kind="batch", arch=graph.name,
        )
        return dep

    def add_onnx(self, name: str, path: str,
                 **deploy_kwargs) -> BatchDeployment:
        """ONNX ingestion: import a foreign graph file and serve it as
        a batch deployment — the imported initializers are the
        variables (imported graphs arrive trained)."""
        from mmlspark_tpu.models.registry import build_model

        graph = build_model("onnx", path=path)
        return self.add_batch(name, graph, graph.init(), **deploy_kwargs)

    # -- lookup -------------------------------------------------------------

    @property
    def models(self) -> list[str]:
        """Deployment names in registration (= scheduling) order."""
        return list(self._order)

    def deployment(self, name: str):
        return self._deployments[self._resolve(name)]

    def _resolve(self, model: str | None) -> str:
        if model is None:
            if len(self._order) == 1:
                return self._order[0]
            raise FriendlyError(
                "this engine serves several models — pass model=<name>; "
                f"deployments: {sorted(self._deployments)}"
            )
        if model in self._deployments:
            return model
        hint = difflib.get_close_matches(
            model, list(self._deployments), n=1,
        )
        suggest = f"; did you mean '{hint[0]}'?" if hint else ""
        raise FriendlyError(
            f"unknown model '{model}'; deployments: "
            f"{sorted(self._deployments)}{suggest}"
        )

    def model_of(self, gid: int) -> str:
        """Which deployment a global request id was routed to."""
        try:
            return self._model_of[gid]
        except KeyError:
            raise FriendlyError(
                f"unknown request id {gid}; ids are the values "
                "submit() returned"
            )

    # -- public API ---------------------------------------------------------

    def submit(self, x, *, model: str | None = None,
               max_new_tokens: int | None = None,
               eos_id: int | None = None,
               deadline_ticks: int | None = None) -> int:
        """Queue one request on the named deployment; returns a GLOBAL
        id (results come back keyed by it). For an LM deployment ``x``
        is the prompt token vector and ``max_new_tokens`` is required;
        for a batch deployment ``x`` is one example and the LM-only
        kwargs are rejected. ``model`` may be omitted only when the
        engine hosts exactly one deployment."""
        name = self._resolve(model)
        dep = self._deployments[name]
        # minted BEFORE the deployment call (the gid is consumed only
        # on success): LM spans on the shared recorder carry the
        # facade-level trace id, so the hub's per-model chains join
        # the "routed" control event to the engine span
        trace = f"m{self._next_gid}"
        if isinstance(dep, ServeEngine):
            if max_new_tokens is None:
                raise FriendlyError(
                    f"deployment '{name}' is an LM — pass "
                    "max_new_tokens= (the decode budget)"
                )
            lid = dep.submit(
                x, max_new_tokens, eos_id=eos_id,
                deadline_ticks=deadline_ticks, trace_id=trace,
            )
        else:
            if (max_new_tokens is not None or eos_id is not None
                    or deadline_ticks is not None):
                raise FriendlyError(
                    "max_new_tokens/eos_id/deadline_ticks configure LM "
                    f"decode; deployment '{name}' is a stateless batch "
                    "deployment (one example in, one result out)"
                )
            lid = dep.submit(x)
        gid = self._next_gid
        self._next_gid += 1
        self._gid[(name, lid)] = gid
        self._model_of[gid] = name
        self.recorder.record(
            "routed", tick=self._tick, model=name, gid=gid, rid=lid,
            trace=trace,
        )
        return gid

    def _has_work(self, name: str) -> bool:
        return self._deployments[name].busy

    def step(self) -> list:
        """One engine tick: walk the round-robin cursor over the
        deployment order, stepping each deployment that has work, up to
        ``device_budget`` dispatches. Returns every request that
        reached a terminal state this tick (``RequestResult`` for LM
        streams, :class:`BatchResult` for batch examples), rekeyed to
        global ids."""
        self._tick += 1
        n = len(self._order)
        if n == 0:
            return []
        budget = self.device_budget if self.device_budget else n
        results: list = []
        ticked = 0
        scanned = 0
        i = self._rr
        while ticked < budget and scanned < n:
            name = self._order[i % n]
            i += 1
            scanned += 1
            if not self._has_work(name):
                continue
            ticked += 1
            for res in self._deployments[name].step():
                gid = self._gid.pop((name, res.id), None)
                if gid is None:
                    # a result for a request submitted directly on the
                    # deployment (bypassing the facade) — surface as-is
                    results.append(res)
                    continue
                results.append(dataclasses.replace(res, id=gid))
        self._rr = i % n
        return results

    @property
    def tick(self) -> int:
        return self._tick

    @property
    def busy(self) -> bool:
        return any(self._has_work(name) for name in self._order)

    def run(self, max_ticks: int = 100_000) -> dict:
        """Step until no deployment has work; results keyed by global
        id. Raises the typed error at ``max_ticks`` with partial
        results attached as ``err.results``."""
        out: dict = {}
        ticks = 0
        with self.recorder.dump_on_friendly_error():
            while self.busy:
                if ticks >= max_ticks:
                    err = FriendlyError(
                        f"MultiModelEngine run() exceeded max_ticks "
                        f"({max_ticks}) with work still queued; "
                        "partial results are attached as err.results"
                    )
                    err.results = dict(out)
                    raise err
                for res in self.step():
                    out[res.id] = res
                ticks += 1
        return out

    # -- metrics ------------------------------------------------------------

    def metrics_dict(self) -> dict:
        """Engine-level totals + one nested per-model dict (each
        deployment's full flat ``to_dict`` schema plus its
        kind/compile-count pins) + the SHARED registry's flat view —
        the ``model{name}.serve.*`` keys tools/check_metrics_schema.py
        gates on the ``--multi-model`` demo line."""
        per_model: dict[str, dict] = {}
        totals = {"submitted": 0, "completed": 0, "failed": 0,
                  "rejected": 0}
        for name in self._order:
            dep = self._deployments[name]
            d = dep.metrics.to_dict()
            for key in totals:
                totals[key] += d[key]
            if isinstance(dep, ServeEngine):
                d["kind"] = "lm"
                d["decode_compile_count"] = dep.decode_compile_count
                d["prefill_compile_count"] = dep.prefill_compile_count
                d["num_decode_blocks"] = dep.num_decode_blocks
                d["num_prefill_buckets"] = dep.num_prefill_buckets
            else:
                d["kind"] = "batch"
                d["batch_compile_count"] = dep.batch_compile_count
                d["num_batch_buckets"] = dep.num_batch_buckets
                d["max_batch"] = dep.max_batch
            per_model[name] = d
        return {
            "multimodel": True,
            "deployments": len(self._order),
            "device_budget": self.device_budget,
            "ticks": self._tick,
            **totals,
            "per_model": per_model,
            # the shared registry's flat exposition-aligned keys:
            # model{name}.serve.ttft_ms.*, model{name}.serve.completed,
            # model{name}.slo.*, ... — ONE dict, no collisions
            "registry": self.registry.to_dict(),
        }

    def to_prometheus(self) -> str:
        """One collision-free Prometheus exposition for every
        deployment (``model{name}_serve_*`` metric families)."""
        return self.registry.to_prometheus()


# ---------------------------------------------------------------------------
# CLI spec grammar (serve --models)
# ---------------------------------------------------------------------------

#: per-entry keys that configure the DEPLOYMENT rather than the model
#: builder (everything else in an entry is build_model config)
_DEPLOY_KEYS = frozenset({
    "slots", "cache_len", "decode_block", "max_queue", "max_batch",
    "slo", "prefill_chunk", "async_host",
})
#: deployment keys valid per kind — crossing them is a spec error
_LM_ONLY = frozenset({
    "slots", "cache_len", "decode_block", "prefill_chunk", "async_host",
})
_BATCH_ONLY = frozenset({"max_batch"})


@dataclasses.dataclass
class ModelSpecEntry:
    """One parsed ``--models`` entry: ``name=arch:key=value:...``."""

    name: str
    arch: str
    build_config: dict
    deploy_kwargs: dict


def _coerce(value: str):
    """CLI value -> int / float / 'x'-separated int tuple / string —
    the same lenient coercion the bench's spec parsers use."""
    try:
        return int(value)
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        pass
    parts = value.split("x")
    if len(parts) > 1 and all(p.isdigit() for p in parts):
        return tuple(int(p) for p in parts)
    return value


def parse_models_spec(spec: str) -> list[ModelSpecEntry]:
    """``--models`` grammar (docs/SERVING.md "Multi-model serving"):
    entries separated by ``;``, each ``name=arch`` followed by
    ``:key=value`` fields. Reserved deployment keys (slots / cache_len /
    decode_block / max_queue / max_batch / slo) configure the
    deployment; every other key is ``build_model`` config (``path=``
    is how an ONNX file registers: ``ox=onnx:path=/path/model.onnx``).
    SLO values spell ``,`` as ``+`` (``slo=ttft_p99_ms=200+error_rate=
    0.5``) because ``:`` and ``;`` are taken. Two more reserved keys,
    ``input_shape`` (``8`` or ``32x32x3``) and ``input_dtype``
    (``int32``/``float32``), patch the built graph's example metadata
    for architectures that record no ``input_shape`` of their own
    (``mlp``/``linear``/``bilstm_tagger``) so spec-built variables can
    initialize::

        lm=transformer_lm:slots=4:cache_len=64;clf=mlp:max_batch=8
    """
    entries: list[ModelSpecEntry] = []
    seen: set[str] = set()
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        fields = raw.split(":")
        head = fields[0]
        if "=" not in head:
            raise FriendlyError(
                f"bad --models entry {head!r}: expected 'name=arch' "
                "(e.g. 'lm=transformer_lm' or 'ox=onnx:path=m.onnx')"
            )
        name, arch = (s.strip() for s in head.split("=", 1))
        if not name or not arch:
            raise FriendlyError(
                f"bad --models entry {raw!r}: empty name or arch"
            )
        if name in seen:
            raise FriendlyError(
                f"duplicate deployment name '{name}' in --models spec"
            )
        seen.add(name)
        build_config: dict = {}
        deploy_kwargs: dict = {}
        for f in fields[1:]:
            if "=" not in f:
                raise FriendlyError(
                    f"bad --models field {f!r} in entry '{name}': "
                    "expected key=value"
                )
            key, value = f.split("=", 1)
            key = key.strip()
            if key == "slo":
                # SLO spec spells ',' as '+' inside the models grammar
                deploy_kwargs[key] = value.replace("+", ",")
            elif key == "path":
                build_config[key] = value
            elif key in _DEPLOY_KEYS:
                deploy_kwargs[key] = _coerce(value)
            else:
                build_config[key] = _coerce(value)
        entries.append(ModelSpecEntry(
            name=name, arch=arch, build_config=build_config,
            deploy_kwargs=deploy_kwargs,
        ))
    if not entries:
        raise FriendlyError(
            "--models spec is empty; expected "
            "'name=arch[:key=value]*[;name=arch...]'"
        )
    return entries


def engine_from_spec(spec: str, *, device_budget: int | None = None,
                     recorder: FlightRecorder | None = None,
                     faults: FaultInjector | None = None,
                     registry: MetricRegistry | None = None,
                     variables: dict | None = None,
                     lm_kwargs: dict | None = None,
                     seed: int = 0) -> MultiModelEngine:
    """Build a :class:`MultiModelEngine` from the CLI spec string.

    Each entry builds its graph via ``build_model(arch, **config)``;
    ONNX entries take their variables from the imported initializers,
    everything else initializes fresh from ``seed`` unless
    ``variables`` maps the deployment name to trained variables (the
    demo passes its trained LM through here). Kind is detected from the
    graph: ``causal`` graphs become LM deployments, everything else a
    batch deployment — and deployment keys of the wrong kind are
    rejected with the offending entry named.
    """
    from mmlspark_tpu.models.registry import build_model

    engine = MultiModelEngine(
        device_budget=device_budget, recorder=recorder, faults=faults,
        registry=registry,
    )
    variables = variables or {}
    for entry in parse_models_spec(spec):
        config = dict(entry.build_config)
        shape = config.pop("input_shape", None)
        input_dtype = config.pop("input_dtype", None)
        graph = build_model(entry.arch, **config)
        if shape is not None:
            shape = (shape,) if isinstance(shape, int) else tuple(shape)
            graph = dataclasses.replace(graph, input_shape=shape)
        causal = bool(graph.extra.get("causal", False))
        wrong = (
            (_BATCH_ONLY if causal else _LM_ONLY)
            & set(entry.deploy_kwargs)
        )
        if wrong:
            kind = "an LM" if causal else "a stateless batch"
            raise FriendlyError(
                f"--models entry '{entry.name}' ({entry.arch}) is "
                f"{kind} deployment; {sorted(wrong)} do not apply"
            )
        if entry.name in variables:
            model_vars = variables[entry.name]
        elif entry.arch == "onnx":
            model_vars = graph.init()
        else:
            model_vars = _init_variables(graph, seed, dtype=input_dtype)
        if causal:
            # lm_kwargs: CLI-wide LM defaults (e.g. --prefill-chunk /
            # --async-host threading through --models); per-entry spec
            # keys win
            engine.add_lm(entry.name, graph, model_vars,
                          **{**(lm_kwargs or {}), **entry.deploy_kwargs})
        else:
            engine.add_batch(entry.name, graph, model_vars,
                             **entry.deploy_kwargs)
    return engine


def _init_variables(graph, seed: int, dtype: str | None = None):
    """Fresh variables for a spec-built graph: thread a zero sample of
    the graph's declared input shape through ``init`` (int32 tokens for
    causal LMs, float32 features otherwise; the spec's ``input_dtype``
    key overrides — e.g. ``bilstm_tagger`` takes int token inputs but
    is not causal)."""
    import jax
    import jax.numpy as jnp

    if not graph.input_shape:
        raise FriendlyError(
            f"'{graph.name}' records no input_shape; spec-built "
            "deployments need it to initialize variables — set the "
            "spec's input_shape= key (e.g. input_shape=8 or 32x32x3) "
            "or pass trained variables explicitly"
        )
    if dtype is None:
        dtype = "int32" if graph.extra.get("causal", False) else "float32"
    sample = jnp.zeros(
        (1,) + tuple(graph.input_shape), jnp.dtype(dtype)
    )
    return graph.init(jax.random.PRNGKey(seed), sample)
