"""Paged KV-cache subsystem: memory virtualization for the slot pool.

``SlotCachePool`` (serve/cache_pool.py) reserves worst-case HBM: one
dense ``(slots, cache_len, hk, d)`` slab per block, every slot paying
for ``cache_len`` positions however short its request is, and identical
prompt prefixes (system prompts, few-shot headers) re-prefilled per
request. :class:`PagedCachePool` virtualizes that memory the way the
TensorFlow-runtime paper virtualizes worker state behind fixed-shape
dataflow steps (arXiv:1605.08695): the DEVICE arrays stay fixed-shape —
so every compiled serving program and its compile-count pins survive
unchanged — while a HOST-side allocator re-maps which physical pages
each slot's logical positions live in.

Layout per transformer block::

    K, V : (num_pages, hk, page_size, d)  bf16   physical page store
    PT   : (slots, max_pages)             int32  per-slot page table

``max_pages = cache_len // page_size``. A slot's logical position ``p``
lives at row ``PT[slot, p // page_size]``, offset ``p % page_size``.
The page store is HEADS-MAJOR (``(hk, page_size, d)`` per page, not the
slot pool's ``(cache_len, hk, d)``) so the paged decode kernel's
``(page_size, d)`` tiles sit on the TPU's sublane×lane axes
(docs/PERFORMANCE.md "Decode path"); ``page_size`` doubles as the
kernel's KV block, keeping the decode grid's shape — and its per-block
math, hence greedy-token parity with the dense pool — unchanged.

Host-side accounting:

- a per-data-shard FREE LIST with refcounts — a page is owned by one
  slot (refcount 1) or SHARED between slots and the prefix cache
  (refcount > 1). Pages allocate from the free list of the owning
  slot's data shard, so under a mesh every page a slot maps lives on
  the shard that already holds the slot's row of the page table
  (the PR 6 placement contract, now per page instead of per slot row).
- physical page ``s * pages_per_shard`` of each shard ``s`` is a
  reserved TRASH page, never allocated: a freed slot's page-table row
  points every entry at it, so the fused decode block's fixed-shape
  writes for dead rows land harmlessly in a page nothing ever reads
  (dead rows decode with live length 0).
- a PREFIX CACHE keyed on the prompt hash: a completed prefill
  registers its pages under its prompt, and a later prompt sharing a
  prefix maps those pages instead of recomputing them —
  COPY-ON-EXTEND, a slot privatizes a shared page only when its write
  frontier enters it (``refcount > 1`` at ``_ensure_writable`` time).
  Sharing is shard-local to keep the placement contract: a hit from a
  slot on another data shard copies the entry's pages onto the slot's
  shard instead of mapping them remotely (the prefill FLOPs are still
  saved). Page pressure evicts the PRESSURED SHARD's least-recently-
  used entries first (other shards' entries free nothing there and
  survive); if the free list is still empty the allocator raises the
  runtime's
  ``RESOURCE_EXHAUSTED`` spelling (:class:`~mmlspark_tpu.core.faults.
  ResourceExhausted`), which the engine's existing degradation ladder
  (PR 7) absorbs: smaller decode blocks, tighter admission, preemption
  at the floor — preempting a slot frees its pages, so pressure costs
  latency, not data.

Device-state discipline: host bookkeeping mutates eagerly BETWEEN
dispatches only. ``ServeEngine`` calls :meth:`ensure_decode_pages`
before each fused block so every page the block can write is mapped and
private up front; during the block the page tables are read-only, which
is what lets the block keep ONE host sync and the donation contract of
PR 5/6 (each transformer block carries its OWN device copy of the page
table — donation forbids aliased leaves).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

import jax
import jax.numpy as jnp

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.faults import ResourceExhausted
from mmlspark_tpu.models.generate import cache_geometry
from mmlspark_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from mmlspark_tpu.serve.cache_pool import (
    kv_head_scales,
    quantize_kv,
    validate_kv_dtype,
)

#: smallest page: the TPU sublane tile — a page's (page_size, d) face is
#: the paged decode kernel's KV block, and blocks under 8 rows cannot
#: tile
MIN_PAGE_SIZE = 8


def default_page_size(cache_len: int) -> int:
    """Smallest multiple of the sublane tile in [8, cache_len] dividing
    ``cache_len``: small pages maximize how much of the pool short
    requests leave free (the point of paging), the kernel's length
    clamp already prices the extra grid steps at zero for dead pages,
    and ``paged_flash_decode`` only tiles pages whose ``(page_size,
    d)`` face is whole sublanes. Raises at build time — not at the
    first decode dispatch — when ``cache_len`` admits no such page
    size."""
    for cand in range(MIN_PAGE_SIZE, cache_len + 1, MIN_PAGE_SIZE):
        if cache_len % cand == 0:
            return cand
    raise FriendlyError(
        f"cache_len ({cache_len}) has no page size that is a multiple "
        f"of {MIN_PAGE_SIZE} (the TPU sublane tile — the paged decode "
        "kernel's KV-block unit) and divides it evenly; round "
        f"cache_len to a multiple of {MIN_PAGE_SIZE} to serve paged"
    )


@dataclasses.dataclass
class _PrefixEntry:
    """One cached prompt prefill: the prompt that produced it, and the
    physical pages holding its K/V (refcounted — the entry itself holds
    one reference per page)."""

    prompt: np.ndarray          # (P,) int32
    length: int                 # P — positions [0, P) are valid
    pages: list[int]            # physical pages covering [0, P)
    last_used: int              # monotonic use counter (LRU eviction)


class PagedCachePool:
    """Drop-in replacement for ``SlotCachePool`` backed by paged
    storage. Same engine-facing surface (``lease``/``free``/
    ``write_prefill``/``buffers``/``positions``/``live``/
    ``kv_shardings``/``device_bytes_per_device``), plus the paging
    plane: :meth:`ensure_decode_pages`, the prefix-cache trio
    (:meth:`prefix_lookup` / :meth:`map_prefix` + :meth:`gather_prefix`
    / :meth:`prefix_insert`), :meth:`paging_stats`, :meth:`snapshot`.

    ``buffers`` is ``{block: (K, V, PT)}`` — the engine's decode jit
    donates and returns the whole pytree unchanged in structure, and
    ``models/transformer.py`` recognizes the 3-tuple as the paged
    cache.

    ``kv_dtype="int8"`` (docs/PERFORMANCE.md "Quantized decode") stores
    the page faces as int8 — half the bf16 page store's HBM bytes, so a
    fixed page budget holds 2x the tokens — and each block's entry
    grows to ``(K, V, PT, k_scale, v_scale)`` with (num_pages, hk) f32
    PER-PAGE scales as extra cache-pytree leaves: a page's scale is
    fixed at its FIRST write (prefill slice amax, or the first decode
    token's amax, + headroom), later writes into the page quantize
    against it, copy-on-extend copies it with the page, and
    ``paged_flash_decode`` dequantizes each fetched page in-VMEM.
    """

    def __init__(self, graph, variables, slots: int, cache_len: int, *,
                 mesh=None, page_size: int | None = None,
                 num_pages: int | None = None,
                 prefix_cache: bool = False, kv_dtype: str = "bf16"):
        if slots < 1:
            raise FriendlyError(f"slots must be >= 1, got {slots}")
        if cache_len < 2:
            raise FriendlyError(
                f"cache_len must be >= 2 (one prompt token + one "
                f"generated), got {cache_len}"
            )
        geometry = cache_geometry(graph, variables)
        if not geometry:
            raise FriendlyError(
                f"'{graph.name}' has no cache-accepting blocks; the "
                "serving engine needs the KV-cache decode path "
                "(transformer_lm family)"
            )
        if page_size is None:
            page_size = default_page_size(cache_len)
        if page_size < MIN_PAGE_SIZE:
            raise FriendlyError(
                f"page_size must be >= {MIN_PAGE_SIZE} (the TPU sublane "
                f"tile — it doubles as the paged decode kernel's KV "
                f"block), got {page_size}"
            )
        if page_size % MIN_PAGE_SIZE:
            raise FriendlyError(
                f"page_size ({page_size}) must be a multiple of "
                f"{MIN_PAGE_SIZE}: paged_flash_decode tiles each page's "
                "(page_size, d) face in whole TPU sublanes and rejects "
                "ragged pages at dispatch time"
            )
        if cache_len % page_size:
            raise FriendlyError(
                f"page_size ({page_size}) must divide cache_len "
                f"({cache_len}): a slot's logical positions tile into "
                "whole pages"
            )
        validate_kv_dtype(kv_dtype, geometry)
        self.kv_dtype = kv_dtype
        self.mesh = mesh
        data = 1
        if mesh is not None:
            data = int(mesh.shape.get(DATA_AXIS, 1))
            if slots % data:
                raise FriendlyError(
                    f"slots ({slots}) must be a multiple of the mesh's "
                    f"'{DATA_AXIS}' axis ({data}): each device in the "
                    "data axis holds slots/data whole page-table rows. "
                    "Round slots up or shrink the axis"
                )
        self.num_slots = slots
        self.cache_len = cache_len
        self.page_size = page_size
        self.max_pages = cache_len // page_size
        self._data = data
        self._slots_per_shard = slots // data
        if num_pages is None:
            # worst case: every slot fully paged, plus one trash page
            # per shard — a budget that can never exhaust. Callers size
            # it DOWN (bench.py serve_paged) to realize the memory win.
            num_pages = data * (self._slots_per_shard * self.max_pages + 1)
        if num_pages % data:
            raise FriendlyError(
                f"num_pages ({num_pages}) must be a multiple of the "
                f"'{DATA_AXIS}' axis ({data}): pages shard over it and "
                "each shard owns its own free list"
            )
        self.num_pages = num_pages
        self._pages_per_shard = num_pages // data
        if self._pages_per_shard < 2:
            raise FriendlyError(
                f"num_pages ({num_pages}) leaves "
                f"{self._pages_per_shard} page(s) per data shard; each "
                "shard needs its reserved trash page plus at least one "
                "allocatable page"
            )
        self.prefix_cache_enabled = bool(prefix_cache)

        quantized = kv_dtype == "int8"
        store_dtype = jnp.int8 if quantized else jnp.bfloat16
        # -- device-placement anchors (None on a single device) -------
        self._slot_sharding = self._kv_shardings = None
        self._pt_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            msize = int(mesh.shape.get(MODEL_AXIS, 1))
            self._slot_sharding = NamedSharding(mesh, P(DATA_AXIS))
            self._pt_sharding = NamedSharding(mesh, P(DATA_AXIS, None))
            self._kv_shardings = {}
            for name, (hk, d) in geometry.items():
                head = (
                    MODEL_AXIS if msize > 1 and hk % msize == 0 else None
                )
                # pages replace slots on the data axis; the allocator
                # below keeps every page a slot maps on the slot's own
                # shard, so page reads/writes stay shard-local
                sh = NamedSharding(mesh, P(DATA_AXIS, head, None, None))
                if quantized:
                    # (num_pages, hk) scale leaves shard like the dims
                    # they index: pages over data, heads over model
                    ssc = NamedSharding(mesh, P(DATA_AXIS, head))
                    self._kv_shardings[name] = (
                        sh, sh, self._pt_sharding, ssc, ssc,
                    )
                else:
                    self._kv_shardings[name] = (sh, sh, self._pt_sharding)

        # -- host allocator state --------------------------------------
        # page table mirror: every entry starts at the owning shard's
        # trash page, so unmapped (and freed) rows absorb the fused
        # block's fixed-shape writes without touching a live page
        self._pt_host = np.empty((slots, self.max_pages), np.int32)
        for slot in range(slots):
            self._pt_host[slot, :] = self._trash_page(
                self._shard_of_slot(slot)
            )
        #: logical pages currently mapped per slot (contiguous [0, n))
        self._npages = [0] * slots
        self._refcount = np.zeros((num_pages,), np.int64)
        # LIFO free lists popping the lowest page id first (the slot
        # pool's determinism convention); trash pages never enter them
        self._free_pages: list[list[int]] = []
        for s in range(data):
            lo, hi = s * self._pages_per_shard, (s + 1) * self._pages_per_shard
            self._free_pages.append(list(range(hi - 1, lo, -1)))
        self._pt_dirty = False

        # -- prefix cache ----------------------------------------------
        #: prompt-hash -> entry (the dict key IS the prompt bytes; its
        #: hash is what the lookup structure indexes on)
        self._prefix: dict[bytes, _PrefixEntry] = {}
        self._use_counter = 0
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        self.prefix_evictions = 0
        self.cow_copies = 0
        #: cross-shard hits localized by page copy (mesh only)
        self.prefix_shard_copies = 0

        # -- device arrays ---------------------------------------------
        self.buffers = {}
        for name, (hk, d) in geometry.items():
            # K and V must be DISTINCT arrays (the engine donates the
            # pytree; one allocation cannot be donated twice) — and so
            # must each block's page-table copy, which is why PT rides
            # per block instead of as one shared array; the int8 mode's
            # two scale leaves follow the same rule
            k = jnp.zeros((num_pages, hk, page_size, d), store_dtype)
            v = jnp.zeros((num_pages, hk, page_size, d), store_dtype)
            pt = jnp.asarray(self._pt_host)
            entry = (k, v, pt)
            if quantized:
                entry = (
                    k, v, pt,
                    jnp.ones((num_pages, hk), jnp.float32),
                    jnp.ones((num_pages, hk), jnp.float32),
                )
            if self._kv_shardings is not None:
                entry = tuple(jax.device_put(
                    entry, self._kv_shardings[name]
                ))
            self.buffers[name] = entry
        self._free = list(range(slots - 1, -1, -1))
        self._leased: set[int] = set()
        # deferred-free window (same contract as SlotCachePool): while
        # the async engine has a decode block in flight, a freed slot's
        # device row state AND page-table row reset immediately (so the
        # NEXT dispatch writes to trash), but its free-list return and
        # page refcount release wait until the stamped generation's
        # block is fetched — the block already in flight writes through
        # the OLD device page table it was dispatched with, so those
        # pages must stay owned until its outputs materialize.
        self._defer_gen: int | None = None
        self._deferred: list[tuple[int, int, list[int]]] = []
        self._deferred_slots: set[int] = set()
        self.positions = self._commit_slot(jnp.zeros((slots,), jnp.int32))
        self.live = self._commit_slot(jnp.zeros((slots,), bool))

    # -- sharding anchors --------------------------------------------------

    def _commit_slot(self, arr):
        if self._slot_sharding is None:
            return arr
        return jax.device_put(arr, self._slot_sharding)

    @property
    def kv_shardings(self):
        """``{block: (K, V, PT) NamedShardings}`` matching ``buffers``
        (what the engine pins decode ``out_shardings`` to), or None
        without a mesh."""
        return self._kv_shardings

    @property
    def slot_sharding(self):
        return self._slot_sharding

    # -- shard geometry ----------------------------------------------------

    def _shard_of_slot(self, slot: int) -> int:
        return slot // self._slots_per_shard

    def _shard_of_page(self, page: int) -> int:
        return page // self._pages_per_shard

    def _trash_page(self, shard: int) -> int:
        return shard * self._pages_per_shard

    def _entry_shard(self, entry: _PrefixEntry) -> int:
        """The data shard holding ALL of an entry's pages:
        ``prefix_insert`` registers one slot's pages (allocated on that
        slot's shard) and ``map_prefix`` copies cross-shard pages local
        before a slot maps them, so an entry never straddles shards."""
        return self._shard_of_page(entry.pages[0])

    # -- page allocator ----------------------------------------------------

    def _alloc_page(self, shard: int) -> int:
        free = self._free_pages[shard]
        if not free:
            self._evict_prefix_entries(shard)
        if not free:
            in_use = self._pages_per_shard - 1
            raise ResourceExhausted(
                f"page allocator exhausted on data shard {shard}: all "
                f"{in_use} allocatable pages are mapped and the prefix "
                "cache has nothing left to evict"
            )
        page = free.pop()
        self._refcount[page] = 1
        return page

    def _decref(self, page: int) -> None:
        rc = int(self._refcount[page])
        if rc <= 0:
            raise FriendlyError(
                f"page {page} refcount underflow (double free: the page "
                "is not mapped by any slot or prefix entry)"
            )
        rc -= 1
        self._refcount[page] = rc
        if rc == 0:
            self._free_pages[self._shard_of_page(page)].append(page)

    def _evict_prefix_entries(self, shard: int) -> None:
        """Free-list pressure valve: drop least-recently-used prefix
        entries whose pages live ON ``shard`` until it has a free page
        (or no remaining entry can free one there). Entries on other
        shards are never touched — evicting them frees nothing on the
        pressured shard, so doing so would wipe unrelated shards'
        cached prefixes and still exhaust. Pages still mapped by active
        slots survive their entry's eviction — the refcount only
        reaches zero once the last slot frees too."""
        while not self._free_pages[shard]:
            local = [
                k for k, e in self._prefix.items()
                if self._entry_shard(e) == shard
            ]
            if not local:
                return
            key = min(local, key=lambda k: self._prefix[k].last_used)
            entry = self._prefix.pop(key)
            for page in entry.pages:
                self._decref(page)
            self.prefix_evictions += 1

    def _ensure_writable(self, slot: int, start: int, stop: int) -> bool:
        """Map — and privatize — the logical pages covering positions
        ``[start, stop)`` of ``slot``. Allocates unmapped pages from
        the slot's shard and COPY-ON-EXTENDs shared ones (refcount > 1:
        the slot's write frontier entered a prefix-cache page). Returns
        whether any K/V page content changed (a CoW copy happened).
        Raises :class:`ResourceExhausted` under page pressure; pages
        mapped before the failure stay accounted to the slot, so a
        later ``free``/preemption releases them."""
        if stop <= start:
            return False
        changed_kv = False
        first_pg = start // self.page_size
        last_pg = (stop - 1) // self.page_size
        shard = self._shard_of_slot(slot)
        for pg in range(min(self._npages[slot], first_pg), last_pg + 1):
            if pg >= self._npages[slot]:
                page = self._alloc_page(shard)
                self._pt_host[slot, pg] = page
                self._npages[slot] = pg + 1
                self._pt_dirty = True
            elif pg >= first_pg:
                phys = int(self._pt_host[slot, pg])
                if int(self._refcount[phys]) > 1:
                    # copy-on-extend: privatize before the write lands
                    page = self._alloc_page(shard)
                    self._copy_page(phys, page)
                    self._decref(phys)
                    self._pt_host[slot, pg] = page
                    self._pt_dirty = True
                    self.cow_copies += 1
                    changed_kv = True
        return changed_kv

    def _copy_page(self, src: int, dst: int) -> None:
        for name, (pk, pv, pt, *scales) in self.buffers.items():
            nk = pk.at[dst].set(pk[src])
            nv = pv.at[dst].set(pv[src])
            if scales:
                # int8 mode: a page copy is only faithful WITH its
                # quantization scales — the copied int8 values decode
                # through the same multipliers as the original's
                ks, vs = scales
                scales = [
                    ks.at[dst].set(ks[src]), vs.at[dst].set(vs[src]),
                ]
            self.buffers[name] = (nk, nv, pt, *scales)

    # -- device-state commits ----------------------------------------------

    def _commit_pt(self) -> None:
        """Materialize the host page table onto the device — one
        DISTINCT array per block (donation forbids aliased leaves),
        committed to the table's canonical sharding under a mesh."""
        if not self._pt_dirty:
            return
        for name, (pk, pv, _old, *scales) in self.buffers.items():
            pt = jnp.asarray(self._pt_host)
            if self._kv_shardings is not None:
                pt = jax.device_put(pt, self._kv_shardings[name][2])
            self.buffers[name] = (pk, pv, pt, *scales)
        self._pt_dirty = False

    def _commit_kv(self) -> None:
        """Re-commit every K/V page store to its canonical sharding
        after eager updates (no-op without a mesh: the functional
        ``.at`` updates already produced fresh arrays) — ONE pinned
        ``device_put`` of the whole pytree, mirroring the slot pool's
        batched update contract."""
        if self._kv_shardings is None:
            return
        # int8 mode: the (num_pages, hk) scale leaves ride the same
        # commit — eager page copies touch them too, and their pinned
        # shardings sit at the same tuple positions in _kv_shardings
        kv = {
            name: (e[0], e[1], *e[3:]) for name, e in self.buffers.items()
        }
        sh = {
            name: (s[0], s[1], *s[3:])
            for name, s in self._kv_shardings.items()
        }
        kv = jax.device_put(kv, sh)
        for name, (k, v, *scales) in kv.items():
            self.buffers[name] = (k, v, self.buffers[name][2], *scales)

    def _commit_slot_pair(self, positions, live) -> None:
        """Rebind positions+live behind ONE pinned update (two
        sequential device_puts would double the eager dispatch count on
        the retire/admit path)."""
        if self._slot_sharding is not None:
            positions, live = jax.device_put(
                (positions, live),
                (self._slot_sharding, self._slot_sharding),
            )
        self.positions, self.live = positions, live

    # -- accounting --------------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def leased_count(self) -> int:
        return len(self._leased)

    def leased_slots(self) -> list[int]:
        """Leased slot ids, ascending — what the engine's kill-parking
        walks to return every held slot (and its page mappings)
        deterministically."""
        return sorted(self._leased)

    @property
    def utilization(self) -> float:
        return len(self._leased) / self.num_slots

    @property
    def pages_free(self) -> int:
        return sum(len(f) for f in self._free_pages)

    @property
    def pages_allocatable(self) -> int:
        """Capacity net of the per-shard reserved trash pages."""
        return self.num_pages - self._data

    def lease(self) -> int:
        if not self._free:
            raise FriendlyError(
                f"no free KV-cache slots (all {self.num_slots} leased); "
                "the scheduler should admit only into free slots — free "
                "a retired slot first or build the pool with more slots"
            )
        slot = self._free.pop()
        self._leased.add(slot)
        return slot

    def defer_frees(self, gen: int) -> None:
        """Open (or advance) a deferred-free window — see
        :meth:`SlotCachePool.defer_frees`. The paged pool's split: the
        slot's PAGE-TABLE row points at the trash page IMMEDIATELY (so
        the next dispatch's dead-row writes are absorbed, exactly like
        a synchronous free), but the pages' refcounts only drop at
        :meth:`flush_frees` — the block already in flight writes
        through the OLD device table it was dispatched with, so its
        frontier page must stay owned until its outputs materialize."""
        self._defer_gen = gen

    def flush_frees(self, completed_gen: int | None = None) -> None:
        """Decref the held pages and return the slot for every deferred
        free whose stamped generation is ``<= completed_gen`` (all when
        None, which also closes the window)."""
        if completed_gen is None:
            self._defer_gen = None
        keep = []
        for gen, slot, pages in self._deferred:
            if completed_gen is None or gen <= completed_gen:
                self._deferred_slots.discard(slot)
                self._leased.discard(slot)
                self._free.append(slot)
                for pg in pages:
                    self._decref(pg)
            else:
                keep.append((gen, slot, pages))
        self._deferred = keep

    def free(self, slot: int) -> None:
        if slot not in self._leased or slot in self._deferred_slots:
            raise FriendlyError(
                f"slot {slot} is not leased (double free, or never "
                f"leased from this pool of {self.num_slots})"
            )
        if self._defer_gen is not None:
            # hold the refcounts, retarget the table: the deferred
            # entry keeps the page ids alive past the in-flight block,
            # while the trash-pointing row reaches every FUTURE
            # dispatch through the commit below
            pages = [
                int(self._pt_host[slot, pg])
                for pg in range(self._npages[slot])
            ]
            self._deferred.append((self._defer_gen, slot, pages))
            self._deferred_slots.add(slot)
            if self._npages[slot]:
                self._pt_host[slot, :] = self._trash_page(
                    self._shard_of_slot(slot)
                )
                self._npages[slot] = 0
                self._pt_dirty = True
        else:
            self._leased.remove(slot)
            self._free.append(slot)
            self._release_mappings(slot)
        self._commit_pt()
        self._commit_slot_pair(
            self.positions.at[slot].set(0),
            self.live.at[slot].set(False),
        )

    def _release_mappings(self, slot: int) -> None:
        """Unmap every logical page of ``slot``: decref (pages shared
        with the prefix cache or other slots survive; exclusive ones
        return to the free list) and point the row back at the trash
        page."""
        if not self._npages[slot]:
            return
        for pg in range(self._npages[slot]):
            self._decref(int(self._pt_host[slot, pg]))
        self._pt_host[slot, :] = self._trash_page(self._shard_of_slot(slot))
        self._npages[slot] = 0
        self._pt_dirty = True

    # -- data path ---------------------------------------------------------

    def write_prefill(self, slot: int, prefill_cache: dict, length: int,
                      start: int = 0) -> None:
        """Scatter a batch-1 LINEAR cache's positions ``[start,
        length)`` into the slot's pages (allocating/privatizing them as
        needed) and mark the slot live at write frontier ``length``.
        ``start > 0`` is the prefix-cache resume path: positions
        ``[0, start)`` are already mapped to shared pages and only the
        remainder lands — the first write into a shared partial page is
        where copy-on-extend fires."""
        if slot not in self._leased:
            raise FriendlyError(f"slot {slot} is not leased")
        if length > self.cache_len:
            raise FriendlyError(
                f"prefill length {length} exceeds the pool's cache_len "
                f"{self.cache_len}"
            )
        if not 0 <= start < length:
            raise FriendlyError(
                f"prefill start ({start}) must lie in [0, length="
                f"{length})"
            )
        self._ensure_writable(slot, start, length)
        pos = np.arange(start, length)
        pages = jnp.asarray(self._pt_host[slot, pos // self.page_size])
        offs = jnp.asarray(pos % self.page_size)
        quantized = self.kv_dtype == "int8"
        for name, (pk, pv, pt, *scales) in self.buffers.items():
            ck, cv = prefill_cache[name][0], prefill_cache[name][1]
            hidx = jnp.arange(pk.shape[1])
            if quantized:
                ks, vs = scales
                # Per-page scales are fixed at each page's FIRST write:
                # a page is fresh here iff its first logical position
                # is at or past ``start`` — the prefix-resume path's
                # shared partial page keeps its registered scale (its
                # already-written half dequantizes through that
                # multiplier; re-deriving one would corrupt it), and
                # the remainder saturates into the budget instead.
                first_pg = start // self.page_size
                last_pg = (length - 1) // self.page_size
                k_rows, v_rows = [], []
                for pg in range(first_pg, last_pg + 1):
                    lo = max(pg * self.page_size, start)
                    hi = min((pg + 1) * self.page_size, length)
                    sk = ck[0, lo:hi].astype(jnp.float32)
                    sv = cv[0, lo:hi].astype(jnp.float32)
                    page = int(self._pt_host[slot, pg])
                    if pg * self.page_size >= start:
                        pks = kv_head_scales(sk, axes=(0, 2))
                        pvs = kv_head_scales(sv, axes=(0, 2))
                        ks = ks.at[page].set(pks)
                        vs = vs.at[page].set(pvs)
                    else:
                        pks, pvs = ks[page], vs[page]
                    k_rows.append(quantize_kv(sk, pks))
                    v_rows.append(quantize_kv(sv, pvs))
                qk = jnp.concatenate(k_rows, axis=0)
                qv = jnp.concatenate(v_rows, axis=0)
                nk = pk.at[
                    pages[:, None], hidx[None, :], offs[:, None]
                ].set(qk)
                nv = pv.at[
                    pages[:, None], hidx[None, :], offs[:, None]
                ].set(qv)
                self.buffers[name] = (nk, nv, pt, ks, vs)
            else:
                nk = pk.at[
                    pages[:, None], hidx[None, :], offs[:, None]
                ].set(ck[0, start:length].astype(pk.dtype))
                nv = pv.at[
                    pages[:, None], hidx[None, :], offs[:, None]
                ].set(cv[0, start:length].astype(pv.dtype))
                self.buffers[name] = (nk, nv, pt)
        self._commit_kv()
        self._commit_pt()
        self._commit_slot_pair(
            self.positions.at[slot].set(length),
            self.live.at[slot].set(True),
        )

    def ensure_decode_pages(self, positions: dict[int, int],
                            t_block: int) -> None:
        """Pre-map every page the next fused decode block can write:
        slot ``s`` at frontier ``p`` writes positions ``[p, p +
        t_block)`` (clipped to ``cache_len``). Called by the engine
        BEFORE the dispatch — the page tables are read-only while the
        block runs, preserving its one-host-sync contract — and inside
        its fault envelope, so :class:`ResourceExhausted` here walks
        the same degradation ladder as a real allocator OOM."""
        changed_kv = False
        for slot, pos in positions.items():
            if slot in self._leased:
                stop = min(pos + t_block, self.cache_len)
                changed_kv |= self._ensure_writable(slot, pos, stop)
        if changed_kv:
            self._commit_kv()
        self._commit_pt()

    # -- prefix cache ------------------------------------------------------

    def prefix_lookup(self, seq, bucket_fn, slot: int | None = None):
        """Best reusable prefix for ``seq``: the cached entry sharing
        the longest common prefix, trimmed to ``keep`` positions such
        that (a) at least one remainder token is left to prefill (its
        logits seed decode), and (b) the remainder's padded bucket
        still fits the linear resume cache (``keep + bucket_fn(len -
        keep) <= cache_len`` — a clamped ``dynamic_update_slice`` would
        corrupt the shared prefix otherwise). With ``slot`` given,
        entries whose pages live on the slot's data shard win coverage
        ties — a same-shard hit maps shared pages for free where a
        cross-shard hit pays :meth:`map_prefix`'s localizing page
        copies. Returns ``(entry, keep)`` or None when nothing covers
        at least one page."""
        if not self._prefix:
            return None
        shard = None if slot is None else self._shard_of_slot(slot)
        seq = np.asarray(seq, np.int32)
        best, best_c, best_local = None, 0, False
        for entry in self._prefix.values():
            m = min(int(seq.size), entry.length)
            if m < best_c:
                continue
            neq = np.nonzero(seq[:m] != entry.prompt[:m])[0]
            c = int(neq[0]) if neq.size else m
            local = shard is None or self._entry_shard(entry) == shard
            if c > best_c or (
                c == best_c and c > 0 and local and not best_local
            ):
                best, best_c, best_local = entry, c, local
        keep = min(best_c, int(seq.size) - 1)
        while (
            keep >= self.page_size
            and keep + bucket_fn(int(seq.size) - keep) > self.cache_len
        ):
            keep -= 1
        if best is None or keep < self.page_size:
            return None
        return best, keep

    def map_prefix(self, slot: int, entry: _PrefixEntry,
                   keep: int) -> bool:
        """Map the entry's pages covering ``[0, keep)`` into ``slot``.
        Pages on the slot's data shard are SHARED (refcounts rise,
        nothing is copied — the prefix prefilled ONCE); pages on
        another shard are copied onto local pages first, preserving the
        per-page placement contract while still skipping the prefix's
        prefill FLOPs. Any mappings the slot already holds are released
        first, making a faulted admit's retry idempotent.

        Returns False — mapping nothing, leaving the slot's existing
        mappings untouched — when the entry is STALE: evicted since the
        lookup (a prior attempt's own page pressure can do that, and
        eviction drops the entry's page references). Mapping a stale
        entry could resurrect pages already on the free list — mapped
        and allocatable at once — so the caller must fall back to a
        full prefill instead. For a registered entry the entry's own
        references pin every page above zero through the re-map, so the
        release below can never free them."""
        if slot not in self._leased:
            raise FriendlyError(f"slot {slot} is not leased")
        if self._prefix.get(entry.prompt.tobytes()) is not entry:
            return False
        self._release_mappings(slot)
        shard = self._shard_of_slot(slot)
        n = -(-keep // self.page_size)  # ceil
        copied = False
        for i in range(n):
            phys = entry.pages[i]
            if self._shard_of_page(phys) == shard:
                self._refcount[phys] += 1
                self._pt_host[slot, i] = phys
            else:
                # localize: an allocator raise here leaves pages [0, i)
                # accounted to the slot (npages tracks the loop), so a
                # retry or free releases them
                page = self._alloc_page(shard)
                self._copy_page(phys, page)
                self._pt_host[slot, i] = page
                self.prefix_shard_copies += 1
                copied = True
            self._npages[slot] = i + 1
            self._pt_dirty = True
        self._use_counter += 1
        entry.last_used = self._use_counter
        self.prefix_hits += 1
        self.prefix_tokens_saved += keep
        if copied:
            self._commit_kv()
        self._commit_pt()
        return True

    def gather_prefix(self, entry: _PrefixEntry, keep: int) -> dict:
        """Linearize the entry's first ``keep`` positions into fresh
        ``(1, cache_len, hk, d)`` caches — the resume program's input
        (the transformer's scalar-pos prefill path wants a linear
        cache; the pool's paged layout is a decode-side format).
        Committed replicated under a mesh so the resume jit sees one
        fixed signature per remainder bucket."""
        n = -(-keep // self.page_size)
        idx = jnp.asarray(np.asarray(entry.pages[:n], np.int32))
        rep = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self.mesh, P())
        out = {}
        for name, (pk, pv, _pt, *scales) in self.buffers.items():
            hk, d = pk.shape[1], pk.shape[3]
            lin = []
            for store, scl in zip((pk, pv), scales or (None, None)):
                g = store[idx]  # (n, hk, ps, d)
                dtype = store.dtype
                if scl is not None:
                    # int8 pages dequantize through their per-page
                    # scales into the bf16 linear cache the resume
                    # program expects (it re-quantizes on write-back)
                    g = g.astype(jnp.float32) * scl[idx][:, :, None, None]
                    dtype = jnp.bfloat16
                g = jnp.swapaxes(g, 1, 2)  # (n, ps, hk, d)
                g = g.reshape(n * self.page_size, hk, d)[:keep]
                arr = jnp.zeros((1, self.cache_len, hk, d), dtype)
                arr = arr.at[0, :keep].set(g.astype(dtype))
                if rep is not None:
                    arr = jax.device_put(arr, rep)
                lin.append(arr)
            out[name] = tuple(lin)
        return out

    def prefix_insert(self, slot: int, seq) -> None:
        """Register ``slot``'s freshly-prefilled pages under its
        prompt. The entry takes one reference per page, keeping the
        K/V alive after the slot retires; a prompt already cached (same
        hash key) is a no-op."""
        seq = np.asarray(seq, np.int32)
        if int(seq.size) < self.page_size:
            return  # can never satisfy a lookup's one-page minimum
        key = seq.tobytes()
        if key in self._prefix:
            return
        n = -(-int(seq.size) // self.page_size)
        pages = [int(self._pt_host[slot, i]) for i in range(n)]
        for page in pages:
            self._refcount[page] += 1
        self._use_counter += 1
        self._prefix[key] = _PrefixEntry(
            prompt=seq.copy(), length=int(seq.size), pages=pages,
            last_used=self._use_counter,
        )

    # -- accounting for telemetry ------------------------------------------

    def device_bytes_per_device(self) -> int:
        """Pool bytes resident PER DEVICE (page stores + page tables +
        per-slot state), shard-shape accounting as the slot pool — the
        figure ``cache_pool_bytes_per_device`` reports. Strictly below
        the dense pool's worst-case reservation whenever ``num_pages <
        slots * max_pages`` (pages not reserved are pages not
        allocated)."""
        total = 0
        arrays = [a for tup in self.buffers.values() for a in tup]
        arrays += [self.positions, self.live]
        for arr in arrays:
            shard = arr.sharding.shard_shape(arr.shape)
            total += math.prod(shard) * arr.dtype.itemsize
        return int(total)

    def paging_stats(self) -> dict:
        """The paging plane's metric keys (schema-gated in
        tools/check_metrics_schema.py)."""
        allocatable = self.pages_allocatable
        free = self.pages_free
        return {
            "page_size": int(self.page_size),
            "pages_total": int(self.num_pages),
            "pages_free": int(free),
            "page_utilization": (
                round((allocatable - free) / allocatable, 4)
                if allocatable else None
            ),
            "prefix_cache_hits_total": int(self.prefix_hits),
            "prefix_cache_entries": len(self._prefix),
            "cow_copies_total": int(self.cow_copies),
            "prefix_tokens_saved_total": int(self.prefix_tokens_saved),
        }

    def refcount_audit(self) -> tuple[int, int]:
        """``(refcount_total, mapped_references)`` — the allocator's
        conservation law. Every unit of refcount must be owned by
        exactly one mapping: a slot page-table entry (``npages`` per
        slot) or a prefix-cache entry's page list. The fleet tests
        assert the two are equal on every replica's pool across a
        hand-off, a failover, and a drain (docs/SERVING.md
        "Disaggregated fleet") — a leak here is silent HBM loss."""
        refcount_total = int(self._refcount.sum())
        mapped = sum(self._npages) + sum(
            len(e.pages) for e in self._prefix.values()
        )
        return refcount_total, int(mapped)

    def snapshot(self) -> dict:
        """JSON-able paging state: page tables, refcounts, prefix-cache
        entries. Informational in restore (the engine re-prefills every
        request bit-identically, rebuilding mappings from scratch) but
        it makes a crash dump auditable: refcount totals must equal
        mapped-page counts, which the round-trip test asserts."""
        return {
            "kv_dtype": self.kv_dtype,
            "page_size": int(self.page_size),
            "num_pages": int(self.num_pages),
            "max_pages": int(self.max_pages),
            "page_table": self._pt_host.tolist(),
            "npages": list(self._npages),
            "refcounts": [int(x) for x in self._refcount],
            "prefix_entries": [
                {
                    "prompt": e.prompt.tolist(),
                    "length": e.length,
                    "pages": list(e.pages),
                    "last_used": e.last_used,
                }
                for e in self._prefix.values()
            ],
            "prefix_cache_hits_total": int(self.prefix_hits),
            "prefix_tokens_saved_total": int(self.prefix_tokens_saved),
            "cow_copies_total": int(self.cow_copies),
        }
