"""Profiling hooks: jax.profiler traces around pipeline work, plus the
unified telemetry plane's public names.

The reference's only tracing is the Timer stage's wall-clock logging
(pipeline-stages/src/main/scala/Timer.scala:14-123) — no sampling profiler
exists (SURVEY.md §5). The TPU build keeps Timer and adds the natural
upgrade the survey calls for: XLA-level traces via ``jax.profiler``,
viewable in TensorBoard/Perfetto, capturing compilation, device compute,
and host↔device transfers.

The structured side — metric registry with latency histograms, trace
spans, the flight recorder, and the retrace watchdog — lives in
:mod:`mmlspark_tpu.core.telemetry` (docs/OBSERVABILITY.md) and is
re-exported here so call sites have ONE observability import next to
the jax.profiler hooks.
"""

from __future__ import annotations

import contextlib
import os

from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.perf import (  # noqa: F401 — re-exports
    DevicePeak,
    PerfAnalytics,
    ProgramCost,
    SloMonitor,
    SloTargets,
    analyze_jit_cost,
    device_peak,
    export_chrome_trace,
    parse_slo_spec,
)
from mmlspark_tpu.core.telemetry import (  # noqa: F401 — re-exports
    Counter,
    FlightRecorder,
    Gauge,
    Histogram,
    MetricRegistry,
    RetraceWatchdog,
    Span,
    SpanTracer,
    default_registry,
    watch_retrace,
)

_log = get_logger("profiling")


@contextlib.contextmanager
def trace_profile(log_dir: str, create_perfetto_link: bool = False):
    """Context manager writing a jax.profiler trace under ``log_dir``.

    Usage::

        with trace_profile("/tmp/trace"):
            model.transform(ds)   # device work captured
    """
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(
        log_dir, create_perfetto_link=create_perfetto_link
    ):
        yield log_dir
    _log.info("profiler trace written under %s", log_dir)


@contextlib.contextmanager
def annotate(name: str):
    """Named region in the device trace (jax.profiler.TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
