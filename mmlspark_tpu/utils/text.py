"""Shared tokenization + feature hashing.

One implementation used by both the featurizers' fit and transform paths
(Featurize's hashed text columns and TextFeaturizer) — fit-time and
transform-time tokenization MUST agree or learned slot alignment silently
diverges. Hashing is ``crc32 % num_features``: process-stable (Python's
``hash`` is salted) and cheap.
"""

from __future__ import annotations

import re
import zlib
from typing import Any

#: compact english stopword list (Spark StopWordsRemover default subset)
STOP_WORDS = frozenset(
    """a an and are as at be but by for if in into is it no not of on or such
    that the their then there these they this to was will with""".split()
)

DEFAULT_PATTERN = r"\W+"


def tokenize(value: Any, config: dict | None = None) -> list[str]:
    """value -> token list. ``config`` keys (all optional): use_tokenizer,
    tokenizer_pattern, to_lowercase, remove_stop_words, use_ngram,
    n_gram_length. Pre-tokenized input (list/tuple/array) passes through
    the post-processing steps only."""
    cfg = config or {}
    if value is None:
        return []
    if isinstance(value, float) and value != value:  # NaN in a real CSV's
        return []  # string column (pandas encodes missing cells this way)
    if isinstance(value, (list, tuple)) or (
        hasattr(value, "dtype") and getattr(value, "ndim", 0) == 1
    ):
        toks = [str(t) for t in value]
    else:
        if not isinstance(value, str):
            value = str(value)  # mixed object column: featurize, not crash
        if cfg.get("use_tokenizer", True):
            v = value.lower() if cfg.get("to_lowercase", True) else value
            toks = [
                t
                for t in re.split(
                    cfg.get("tokenizer_pattern", DEFAULT_PATTERN), v
                )
                if t
            ]
        else:
            toks = [value]
    if cfg.get("remove_stop_words"):
        toks = [t for t in toks if t.lower() not in STOP_WORDS]
    if cfg.get("use_ngram"):
        n = cfg.get("n_gram_length", 2)
        toks = [" ".join(toks[i : i + n]) for i in range(len(toks) - n + 1)]
    return toks


def hash_token(token: str, num_features: int) -> int:
    return zlib.crc32(token.encode("utf-8")) % num_features
