"""Small shared utilities."""
