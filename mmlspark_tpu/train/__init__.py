"""In-process SPMD training (the reference's external `mpiexec cntk` path
re-expressed as a jit-compiled sharded train step — SURVEY.md §2.5 row 2)."""

from mmlspark_tpu.train.resilience import (  # noqa: F401
    AtomicCheckpointStore,
    next_accum_rung,
)
from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig  # noqa: F401
