"""SPMD data-parallel trainer with step-level checkpointing and
fault-tolerant execution.

Reference training path (CNTKLearner.fit, cntk-train/src/main/scala/
CNTKLearner.scala:52-162): export the whole dataset to a text file, generate
BrainScript, launch ``mpiexec -n <#GPUs> cntk ... parallelTrain=true`` and let
CNTK's MPI ring do data-parallel SGD; no mid-training resume (SURVEY.md §5).

TPU-native replacement, per BASELINE.json's north star:
- no file round-trip: host batches feed device HBM directly
  (:mod:`mmlspark_tpu.data.feed`),
- the MPI ring becomes ONE jit-compiled train step over a named mesh —
  batches sharded on the ``data`` axis, params replicated; XLA compiles the
  gradient reduction to an all-reduce over ICI (the `lax.psum` the north star
  names appears implicitly from the sharding annotations; scaling-book
  recipe),
- ``TrainConfig`` replaces generated BrainScript (BrainscriptBuilder.scala),
- step-level checkpoint/resume via an atomically-committed manifest over
  orbax (:mod:`mmlspark_tpu.train.resilience`) — a capability upgrade the
  survey flags as required (§5 checkpoint/resume).

Resilience (docs/TRAINING.md): the trainer fires the four ``train.*``
fault hook sites (core/faults.py) and survives each of them —
transient step/data faults are retried with capped deterministic
backoff, ``RESOURCE_EXHAUSTED`` walks a power-of-two
gradient-accumulation ladder instead of dying, non-finite or exploding
gradients are quarantined IN-GRAPH (params, optimizer state, and model
stats all revert to the pre-step values, so a skipped step is a pure
data advance), and a ``kill`` is the crash the bit-exact-resume drill
restores from: the atomic checkpoint carries params, optimizer state,
the anomaly streak, the step count, and the loss history, and the
seed-deterministic data order makes the resumed run bit-identical to
an uninterrupted one. Every hook is one ``is not None`` check when
``faults`` is None (the ``train_resilience`` bench group pins the
overhead to noise).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Iterator

import numpy as np

from mmlspark_tpu.core.exceptions import FriendlyError, ParamError
from mmlspark_tpu.core.faults import (
    EngineKilled,
    FaultInjector,
    is_resource_exhausted,
    is_transient,
)
from mmlspark_tpu.core.integrity import CheckpointCorruption
from mmlspark_tpu.core.logging_utils import get_logger
from mmlspark_tpu.core.telemetry import FlightRecorder, MetricRegistry
from mmlspark_tpu.models.graph import NamedGraph
from mmlspark_tpu.parallel.mesh import DATA_AXIS, batch_spec, make_mesh, replicated_spec

_log = get_logger("train")

SOFTMAX_XENT = "softmax_xent"
SIGMOID_XENT = "sigmoid_xent"
MSE = "mse"


@dataclass(frozen=True)
class TrainConfig:
    """Everything the generated BrainScript used to say
    (BrainscriptBuilder.toOverrideConfig, BrainscriptBuilder.scala:103-115),
    as a typed config object."""

    epochs: int = 1
    batch_size: int = 128  # global batch; split over the data axis
    learning_rate: float = 1e-3
    optimizer: str = "adam"  # adam | adamw | sgd | momentum
    loss: str = SOFTMAX_XENT
    weight_decay: float = 0.0
    momentum: float = 0.9
    lr_schedule: str = "constant"  # constant | cosine
    warmup_steps: int = 0
    seed: int = 0
    log_every: int = 50
    shuffle: bool = True
    # chain K optimizer steps inside ONE compiled call (lax.scan over K
    # stacked batches): cuts per-step host dispatch to 1/K — decisive on
    # high-latency links (TPU behind a relay). Semantics are exact: every
    # batch is still one optimizer step; epoch tails that don't fill a
    # chunk run through the single-step program. Ignored (forced 1) under
    # tensor-parallel param_rules.
    steps_per_dispatch: int = 1
    # rematerialize the forward pass in the backward (jax.checkpoint):
    # trades ~33% more FLOPs for not keeping activations in HBM — the
    # standard lever when activation memory, not compute, caps batch size
    remat: bool = False
    # accumulate gradients over K equal micro-batches inside one
    # optimizer step (lax.scan over the split batch): the effective
    # batch stays batch_size while activation memory drops to 1/K — the
    # complementary lever to remat when memory caps the batch. Exact for
    # mean losses over equal micro-batches (grads are averaged before
    # the single optimizer update). NOT bit-equivalent for MoE models:
    # sown auxiliary losses (load-balance) are computed per micro-batch
    # and averaged, so expert routing balances within each micro-batch
    # rather than across the full batch — a slightly different (still
    # unbiased-in-spirit, standard-practice) estimator than accum=1.
    grad_accum: int = 1
    # weight on sown auxiliary losses (e.g. MoE load-balance, models/moe.py)
    moe_aux_weight: float = 1e-2
    # mesh: axis name -> size; None = all devices on the data axis
    mesh_axes: dict | None = None
    # tensor-parallel param sharding rules: ordered (regex, spec_tuple)
    # pairs (see parallel/sharding.py, e.g. TRANSFORMER_TP_RULES); None =
    # fully replicated params (the reference's only strategy)
    param_rules: Any = None
    # step-level checkpointing (train/resilience.py atomic store)
    checkpoint_dir: str | None = None
    checkpoint_every: int = 0  # steps; 0 = only at end
    max_checkpoints: int = 3
    resume: bool = True
    # -- resilience knobs (docs/TRAINING.md) ----------------------------
    # abort (FriendlyError + flight-recorder dump) after this many
    # CONSECUTIVE quarantined steps; the host check syncs at log_every
    # cadence, so the abort lags the Nth bad step by < log_every steps.
    # 0 disables the abort (quarantine still skips each bad step).
    anomaly_limit: int = 5
    # grad-norm explosion threshold for the quarantine predicate; 0 =
    # only non-finite loss/grad_norm count as anomalies
    max_grad_norm: float = 0.0
    # capped retries for transient train.step/train.data/train.restore
    # faults, with deterministic linear backoff retry_backoff_s*attempt
    retry_limit: int = 3
    retry_backoff_s: float = 0.0
    # integrity audit cadence (docs/TRAINING.md "Integrity audits"):
    # every N steps the compiled step folds a bitcast-uint32 checksum
    # of params+optimizer state into its donated carry, and the host
    # cross-checks every data-parallel replica's copy for bit-identity
    # (silent-data-corruption detection; a mismatch quarantines the
    # divergent replica and runs the deterministic-replay adjudicator).
    # 0 disables the audit — the step program is then byte-identical
    # to an integrity-unaware build, so default runs pay nothing.
    audit_every: int = 0


def _make_optimizer(cfg: TrainConfig, total_steps: int):
    import optax

    if cfg.lr_schedule == "cosine":
        lr: Any = optax.warmup_cosine_decay_schedule(
            0.0, cfg.learning_rate, max(cfg.warmup_steps, 1),
            max(total_steps, 2),
        )
    elif cfg.warmup_steps > 0:
        lr = optax.linear_schedule(0.0, cfg.learning_rate, cfg.warmup_steps)
    else:
        lr = cfg.learning_rate
    if cfg.optimizer == "adam":
        return optax.adam(lr)
    if cfg.optimizer == "adamw":
        return optax.adamw(lr, weight_decay=cfg.weight_decay)
    if cfg.optimizer == "sgd":
        return optax.sgd(lr)
    if cfg.optimizer == "momentum":
        return optax.sgd(lr, momentum=cfg.momentum)
    raise ParamError(f"unknown optimizer '{cfg.optimizer}'")


def masked_loss(kind: str, logits, labels, mask):
    """Mask-weighted mean loss. The mask marks real (non-padding) rows so
    fixed-shape batches never skew gradients."""
    import jax.numpy as jnp
    import optax

    w = mask.astype(jnp.float32)
    if logits.ndim == 3:
        # sequence model: (B, T, C) -> per-token loss, row mask broadcast
        # over T (padding rows weight 0 for every token)
        w = w[:, None] * jnp.ones(logits.shape[:2], jnp.float32)
    if kind == SOFTMAX_XENT:
        per = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels.astype(jnp.int32)
        )
    elif kind == SIGMOID_XENT:
        per = optax.sigmoid_binary_cross_entropy(
            logits[..., 0], labels.astype(jnp.float32)
        )
    elif kind == MSE:
        pred = logits[..., 0] if logits.ndim > w.ndim else logits
        per = jnp.square(pred - labels.astype(jnp.float32))
    else:
        raise ParamError(f"unknown loss '{kind}'")
    return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1.0)


def _sown_aux_loss(variables: dict):
    """Sum of every value sown into a block's ``losses`` collection (MoE
    load-balance terms, models/moe.py); 0.0 when none exist."""
    import jax

    total = 0.0
    for block_vars in variables.values():
        if isinstance(block_vars, dict) and "losses" in block_vars:
            for leaf in jax.tree_util.tree_leaves(block_vars["losses"]):
                total = total + leaf.sum()
    return total


def _split_variables(variables: dict) -> tuple[dict, dict]:
    """Per-block variables -> (trainable params tree, static/stats tree).

    Sown per-call ``losses`` are consumed by :func:`_sown_aux_loss` before
    this split and must NOT ride along in ``rest``: they would change the
    carried tree structure after step 0 (forcing a recompile and breaking
    checkpoint restore against the init-derived target).
    """
    params = {b: v.get("params", {}) for b, v in variables.items()}
    rest = {
        b: {k: c for k, c in v.items() if k not in ("params", "losses")}
        for b, v in variables.items()
    }
    return params, rest


def _merge_variables(params: dict, rest: dict) -> dict:
    return {b: {"params": params[b], **rest.get(b, {})} for b in params}


class SPMDTrainer:
    """Train a NamedGraph with one compiled sharded step.

    ``train(x, y)`` owns the epoch loop; the per-step program is compiled
    once (fixed shapes from the feed layer) and reused — the analog of the
    reference's single external training run, minus the process boundary.

    ``faults`` (a :class:`~mmlspark_tpu.core.faults.FaultInjector`, or
    None) drives the ``train.*`` drill sites; ``recorder`` collects the
    step/checkpoint/restore/anomaly/retry/degraded event timeline
    (docs/TRAINING.md "Failure semantics").
    """

    def __init__(self, graph: NamedGraph, config: TrainConfig,
                 telemetry: MetricRegistry | None = None,
                 recorder: FlightRecorder | None = None,
                 faults: FaultInjector | None = None):
        self.graph = graph
        self.config = config
        self.history: list[dict] = []
        #: loss-curve entries carried over from a restored checkpoint's
        #: manifest — kept SEPARATE from :attr:`history` (this run's own
        #: curve) so step arithmetic over ``history`` is resume-invariant;
        #: ``restored_history + history`` is the full curve and is what
        #: the next checkpoint persists
        self.restored_history: list[dict] = []
        #: per-trainer metric registry (core/telemetry): step-time,
        #: tokens/sec, loss, and grad-norm histograms, recorded at
        #: ``log_every`` cadence — ``telemetry.to_dict()`` is the flat
        #: percentile view (docs/OBSERVABILITY.md)
        self.telemetry = telemetry if telemetry is not None \
            else MetricRegistry()
        #: flight recorder (core/telemetry): the trainer's event
        #: timeline, dumped automatically when a FriendlyError (e.g.
        #: the anomaly abort) escapes ``train()``
        self.recorder = recorder if recorder is not None \
            else FlightRecorder()
        self._faults = faults
        self._step = 0  # current global step, for the fault listener's tick
        if faults is not None and faults.listener is None:
            # injected faults land in the same metrics + event timeline
            # as their consequences (retries, quarantines, degradation)
            def _on_fault(kind: str, site: str) -> None:
                self.telemetry.counter("train.faults_injected_total").inc()
                self.recorder.record(
                    "fault_injected", tick=self._step, kind=kind, site=site,
                )
            faults.listener = _on_fault
        #: deterministic-replay adjudications, newest last: each entry
        #: names the audit step, the verdict ("transient_sdc" when the
        #: replay reproduces the majority/device checksum — the flip
        #: was isolated corruption of a copy at rest — or
        #: "software_nondeterminism" when the recomputation itself
        #: disagrees), and the three checksums compared
        self.replay_verdicts: list[dict] = []
        # pre-created so the exported schema is stable whether or not a
        # fault ever fires (tools/check_metrics_schema.py --train)
        for name in ("train.retries_total", "train.anomalies_skipped",
                     "train.checkpoints", "train.checkpoint_failures",
                     "train.faults_injected_total",
                     "train.integrity.audits",
                     "train.integrity.checksum_failures",
                     "train.integrity.sdc_suspected",
                     "train.integrity.replay_transient_sdc",
                     "train.integrity.replay_software_nondeterminism"):
            self.telemetry.counter(name)
        self.telemetry.gauge("train.grad_accum").set(
            max(int(config.grad_accum), 1)
        )

    # -- checkpointing ------------------------------------------------------

    def _ckpt_store(self):
        cfg = self.config
        if not cfg.checkpoint_dir:
            return None
        from mmlspark_tpu.train.resilience import AtomicCheckpointStore

        def pre_commit(step: int) -> None:
            # the torn-write drill window: fires between the payload
            # write and the manifest commit (docs/TRAINING.md
            # "Checkpoint atomicity")
            if self._faults is not None:
                self._faults.fire("train.checkpoint", tick=step)

        def post_hash(step: int, payload_dir: str) -> None:
            # the silent-corruption drill window: a corrupt fault here
            # bit-flips the payload AFTER its sha256 was taken, so the
            # manifest commits a hash the bytes no longer match —
            # detected only when a verified restore looks
            if self._faults is None:
                return
            seed = self._faults.corrupt_spec("train.checkpoint",
                                             tick=step)
            if seed is not None:
                from mmlspark_tpu.core import integrity

                integrity.flip_bit_in_dir(payload_dir, seed)

        return AtomicCheckpointStore(
            cfg.checkpoint_dir, max_to_keep=cfg.max_checkpoints,
            pre_commit=pre_commit, post_hash=post_hash,
        )

    # -- fault hooks --------------------------------------------------------

    def _fire_hook(self, site: str, tick: int) -> None:
        """Fire one fault hook site; transient faults are absorbed by up
        to ``retry_limit`` retries with deterministic linear backoff.
        Fired BEFORE the guarded work (dispatch, batch use, restore
        read) so a raised fault never consumes donated buffers and a
        retry is always safe. OOM/kill escape to the caller's policy."""
        if self._faults is None:
            return
        cfg = self.config
        attempt = 0
        while True:
            try:
                self._faults.fire(site, tick=tick)
                return
            except Exception as e:
                if is_transient(e) and attempt < cfg.retry_limit:
                    attempt += 1
                    self.telemetry.counter("train.retries_total").inc()
                    self.recorder.record(
                        "retry", tick=tick, site=site, attempt=attempt,
                    )
                    if cfg.retry_backoff_s:
                        time.sleep(cfg.retry_backoff_s * attempt)
                    continue
                raise

    # -- main loop ----------------------------------------------------------

    def train(
        self,
        x: np.ndarray,
        y: np.ndarray,
        init_variables: dict | None = None,
        eval_fn: Callable[[dict], dict] | None = None,
    ) -> dict:
        """Run the configured number of epochs over (x, y); returns trained
        variables. Resumes from the newest committed checkpoint when
        configured. A :class:`FriendlyError` escaping this call (the
        anomaly-streak abort, an exhausted accumulation ladder) dumps
        the flight recorder first — the black-box contract."""
        with self.recorder.dump_on_friendly_error():
            return self._train_impl(x, y, init_variables, eval_fn)

    def _train_impl(self, x, y, init_variables, eval_fn) -> dict:
        import jax
        import jax.numpy as jnp
        import optax

        from mmlspark_tpu.train.resilience import next_accum_rung

        cfg = self.config
        n = len(x)
        if n == 0:
            raise FriendlyError("empty training set")
        mesh = make_mesh(cfg.mesh_axes)
        n_data = mesh.shape.get(DATA_AXIS, 1)
        batch = cfg.batch_size
        if batch % n_data:
            batch += n_data - batch % n_data
        steps_per_epoch = -(-n // batch)  # ceil: batch_iterator pads the tail
        total_steps = steps_per_epoch * cfg.epochs
        tx = _make_optimizer(cfg, total_steps)

        rng = jax.random.PRNGKey(cfg.seed)
        if init_variables is None:
            sample = jnp.asarray(x[:1])
            init_variables = self.graph.init(rng, sample)
        params, rest = _split_variables(init_variables)
        opt_state = tx.init(params)
        step0 = 0
        # in-graph anomaly carries: consecutive-bad-step streak and the
        # cumulative quarantined-step count, donated alongside the state
        # so the quarantine costs no extra host syncs
        streak0 = np.zeros((), np.int32)
        anoms0 = np.zeros((), np.int32)
        seen_anoms = 0  # last total synced into the per-run counter

        store = self._ckpt_store()
        restored = None
        meta: dict = {}
        latest: int | None = None
        if store is not None and cfg.resume and store.latest_step() is not None:
            latest = store.latest_step()
            target = {
                "params": jax.device_get(params),
                "rest": jax.device_get(rest),
                "opt_state": jax.device_get(opt_state),
                "anomaly": {"streak": streak0, "total": anoms0},
            }
            while latest is not None:
                # train.restore drill site: transient -> retried read,
                # kill -> the restore itself crashed (escape)
                self._fire_hook("train.restore", latest)
                try:
                    restored, meta, latest = store.restore(target)
                    break
                except CheckpointCorruption as e:
                    # verified restore (docs/TRAINING.md "Integrity
                    # audits"): the store already quarantined the
                    # corrupt step, so the retry lands on the previous
                    # committed checkpoint — or a cold start when no
                    # intact checkpoint remains
                    self.telemetry.counter(
                        "train.integrity.checksum_failures"
                    ).inc()
                    self.recorder.record(
                        "integrity.checksum_failure", tick=e.step,
                        surface="checkpoint", expected=e.expected,
                        actual=e.actual,
                    )
                    _log.warning("%s", e)
                    latest = store.latest_step()
        if restored is not None:
            params = restored["params"]
            rest = restored["rest"]
            opt_state = restored["opt_state"]
            streak0 = restored["anomaly"]["streak"]
            anoms0 = restored["anomaly"]["total"]
            seen_anoms = int(anoms0)
            self.restored_history = list(meta.get("history", []))
            spe = meta.get("steps_per_epoch")
            if spe is not None and int(spe) != steps_per_epoch:
                raise FriendlyError(
                    f"checkpoint at {cfg.checkpoint_dir!r} was taken with "
                    f"steps_per_epoch={spe} but this run computes "
                    f"{steps_per_epoch} (batch {batch} over {n_data} data "
                    "shards): elastic resume needs a batch_size divisible "
                    "by both the old and new data-axis widths so the "
                    "deterministic data order is unchanged"
                )
            step0 = latest + 1
            self.recorder.record("restore", tick=latest,
                                 anomalies_total=seen_anoms)
            _log.info("resumed from checkpoint step %d", latest)

        data_sh = batch_spec(mesh)
        rep_sh = replicated_spec(mesh)
        graph = self.graph
        loss_kind = cfg.loss

        aux_w = cfg.moe_aux_weight
        max_gnorm = float(cfg.max_grad_norm)
        # forward the padding mask only to graphs that accept it (user
        # duck-typed graphs may predate the mask kwarg)
        import inspect

        takes_mask = "mask" in inspect.signature(graph.apply).parameters

        def fwd(variables, bx, bmask):
            mask_kw = {"mask": bmask} if takes_mask else {}
            return graph.apply(variables, bx, train=True, **mask_kw)

        if cfg.remat:
            # recompute the forward during the backward instead of holding
            # activations in HBM
            fwd = jax.checkpoint(fwd)

        accum = max(int(cfg.grad_accum), 1)
        if accum > 1 and batch % (accum * n_data):
            raise FriendlyError(
                f"grad_accum={accum} needs the (data-axis rounded) batch "
                f"size {batch} divisible by accum x data-axis size "
                f"({accum * n_data})"
            )

        audit_every = max(int(cfg.audit_every), 0)
        audit = audit_every > 0

        def make_step_fn(accum: int, audit: bool = False):
            """One optimizer step at the given accumulation rung, with the
            in-graph anomaly quarantine fused at the end.

            With ``audit`` the signature grows a donated uint32 checksum
            carry plus a ``do_audit`` flag: on audit steps a bitcast
            fold of the post-step params + optimizer state
            (:func:`~mmlspark_tpu.core.integrity.tree_checksum`)
            replaces the carry under ``lax.cond`` — non-audit steps
            skip the fold entirely, and the host only reads the carry
            at audit cadence, so the audit adds no per-step host
            sync (docs/TRAINING.md "Integrity audits")."""

            def step_fn(params, rest, opt_state, streak, anoms,
                        bx, by, bmask):
                def loss_fn(p, r, mx, my, mm):
                    variables = _merge_variables(p, r)
                    out, updated = fwd(variables, mx, mm)
                    loss = masked_loss(loss_kind, out, my, mm)
                    loss = loss + aux_w * _sown_aux_loss(updated)
                    _, new_rest = _split_variables(updated)
                    return loss, new_rest

                if accum == 1:
                    (loss, new_rest), grads = jax.value_and_grad(
                        loss_fn, has_aux=True
                    )(params, rest, bx, by, bmask)
                else:
                    # micro-batch scan: grads sum in f32 param space, ONE
                    # optimizer update at the end — activations for only one
                    # micro-batch are ever live. Two exactness details:
                    # - STRIDED split (row i -> micro i % accum): each
                    #   device's contiguous data-axis shard feeds every
                    #   micro-batch locally (a contiguous split would move
                    #   whole micro-batches across the mesh every step), and
                    #   the padded tail spreads over micro-batches;
                    # - WEIGHTED accumulation: each micro contributes its
                    #   masked loss SUM and mask count, normalized once at
                    #   the end — uniform averaging of per-micro means would
                    #   shrink the step by up to accum when padding
                    #   concentrates in some micro-batches (masked_loss
                    #   normalizes by its own batch's count).
                    split = lambda t: t.reshape(  # noqa: E731
                        t.shape[0] // accum, accum, *t.shape[1:]
                    ).swapaxes(0, 1)

                    def sum_loss_fn(p, r, mx, my, mm):
                        l, r2 = loss_fn(p, r, mx, my, mm)
                        cnt = jnp.sum(mm.astype(jnp.float32))
                        return l * jnp.maximum(cnt, 1.0), (r2, cnt)

                    def body(carry, xs):
                        gsum, lsum, csum, r = carry
                        (ls, (r, cnt)), g = jax.value_and_grad(
                            sum_loss_fn, has_aux=True
                        )(params, r, *xs)
                        gsum = jax.tree_util.tree_map(jnp.add, gsum, g)
                        return (gsum, lsum + ls, csum + cnt, r), None

                    zero = jax.tree_util.tree_map(jnp.zeros_like, params)
                    f0 = jnp.asarray(0.0, jnp.float32)
                    (gsum, lsum, csum, new_rest), _ = jax.lax.scan(
                        body,
                        (zero, f0, f0, rest),
                        (split(bx), split(by), split(bmask)),
                    )
                    denom = jnp.maximum(csum, 1.0)
                    grads = jax.tree_util.tree_map(
                        lambda t: t / denom, gsum
                    )
                    loss = lsum / denom
                # global grad norm BEFORE the optimizer transform: the
                # scale-blowup/vanishing signal the telemetry histograms
                # track — one extra scalar through the existing fetch
                gnorm = optax.global_norm(grads)
                updates, new_opt = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                # grad-anomaly quarantine (docs/TRAINING.md): a non-finite
                # loss/grad-norm (or an explosion past max_grad_norm)
                # reverts params, optimizer state, AND model stats to the
                # pre-step values — the update is skipped entirely and
                # the optimizer's own step count does not advance. On a
                # healthy step every select picks the new leaf, so the
                # quarantine is bit-invisible to anomaly-free runs.
                bad = jnp.logical_or(
                    jnp.logical_not(jnp.isfinite(loss)),
                    jnp.logical_not(jnp.isfinite(gnorm)),
                )
                if max_gnorm > 0.0:
                    bad = jnp.logical_or(bad, gnorm > max_gnorm)

                def keep(new, old):
                    return jax.tree_util.tree_map(
                        lambda nl, ol: jnp.where(bad, ol, nl), new, old
                    )

                new_params = keep(new_params, params)
                new_opt = keep(new_opt, opt_state)
                new_rest = keep(new_rest, rest)
                streak = jnp.where(bad, streak + 1,
                                   jnp.zeros_like(streak))
                anoms = anoms + bad.astype(anoms.dtype)
                return (new_params, new_rest, new_opt, streak, anoms,
                        loss, gnorm)

            if not audit:
                return step_fn

            from mmlspark_tpu.core.integrity import tree_checksum

            def step_audit(params, rest, opt_state, streak, anoms, chk,
                           bx, by, bmask, do_audit):
                (new_params, new_rest, new_opt, streak, anoms, loss,
                 gnorm) = step_fn(params, rest, opt_state, streak,
                                  anoms, bx, by, bmask)
                chk2 = jax.lax.cond(
                    do_audit,
                    lambda p, o: tree_checksum((p, o)),
                    lambda p, o: chk,
                    new_params, new_opt,
                )
                return (new_params, new_rest, new_opt, streak, anoms,
                        chk2, loss, gnorm)

            return step_audit

        k_steps = max(int(cfg.steps_per_dispatch), 1)
        if cfg.param_rules:
            k_steps = 1  # TP branch compiles without explicit shardings

        if cfg.param_rules:
            # tensor parallelism: shard params per rule set; optimizer
            # state inherits each param's sharding (GSPMD propagates
            # through tx.init), and the train step is compiled without
            # explicit shardings — committed inputs drive GSPMD, which
            # inserts the ICI collectives.
            from mmlspark_tpu.parallel.sharding import build_param_shardings

            param_sh = build_param_shardings(params, mesh, cfg.param_rules)
            params = jax.device_put(params, param_sh)
            opt_template = jax.jit(tx.init)(params)
            mesh_devs = set(mesh.devices.flat)

            def _opt_sharding(leaf):
                # leaves tx.init derived from params keep the param
                # sharding; fresh scalars (step counts) land on one device
                # and must be re-replicated over the mesh
                if set(leaf.sharding.device_set) == mesh_devs:
                    return leaf.sharding
                return rep_sh

            opt_state = jax.tree_util.tree_map(
                lambda t, v: jax.device_put(
                    jnp.asarray(v), _opt_sharding(t)
                ),
                opt_template,
                opt_state,
            )
            rest = jax.device_put(rest, rep_sh)
        else:
            params = jax.device_put(params, rep_sh)
            rest = jax.device_put(rest, rep_sh)
            opt_state = jax.device_put(opt_state, rep_sh)
        streak_dev = jax.device_put(jnp.asarray(streak0, jnp.int32), rep_sh)
        anoms_dev = jax.device_put(jnp.asarray(anoms0, jnp.int32), rep_sh)

        from jax.sharding import NamedSharding, PartitionSpec as P

        # batch dim is axis 1 of the (K, batch, ...) stacks
        chunk_sh = NamedSharding(mesh, P(None, DATA_AXIS))

        def build_programs(accum: int):
            """Compile the step (and K-step chunk) programs at one
            accumulation rung. Called once up front and once per rung
            the OOM degrade ladder descends to — one compile per rung,
            the same honesty as serve's decode-block ladder. With
            audits on, every program carries the extra donated uint32
            checksum slot; with audits off the signatures are exactly
            the pre-integrity ones (bit-identical programs)."""
            step_fn = make_step_fn(accum, audit)
            n_carry = 6 if audit else 5
            n_out = 8 if audit else 7
            if cfg.param_rules:
                jitted = jax.jit(
                    step_fn, donate_argnums=tuple(range(n_carry))
                )
                return jitted, None
            in_sh = (rep_sh,) * n_carry + (data_sh,) * 3
            if audit:
                in_sh = in_sh + (rep_sh,)
            jitted = jax.jit(
                step_fn,
                in_shardings=in_sh,
                out_shardings=(rep_sh,) * n_out,
                donate_argnums=tuple(range(n_carry)),
            )
            chunk_jitted = None
            if k_steps > 1:
                inner = make_step_fn(accum, False)

                def scan_chunk(params, rest, opt_state, streak, anoms,
                               bxs, bys, bms):
                    def body(carry, xs):
                        p, r, o, s, a = carry
                        p, r, o, s, a, loss, gnorm = inner(
                            p, r, o, s, a, *xs
                        )
                        return (p, r, o, s, a), (loss, gnorm)

                    return jax.lax.scan(
                        body, (params, rest, opt_state, streak, anoms),
                        (bxs, bys, bms),
                    )

                if audit:
                    from mmlspark_tpu.core.integrity import tree_checksum

                    def chunk_fn(params, rest, opt_state, streak, anoms,
                                 chk, bxs, bys, bms, do_audit):
                        (params, rest, opt_state, streak, anoms), \
                            (losses, gnorms) = scan_chunk(
                                params, rest, opt_state, streak, anoms,
                                bxs, bys, bms,
                            )
                        # audit cadence coarsens to the dispatch-chunk
                        # boundary, the same honesty as the log cadence
                        chk2 = jax.lax.cond(
                            do_audit,
                            lambda p, o: tree_checksum((p, o)),
                            lambda p, o: chk,
                            params, opt_state,
                        )
                        return (params, rest, opt_state, streak, anoms,
                                chk2, losses[-1], gnorms[-1])
                else:
                    def chunk_fn(params, rest, opt_state, streak, anoms,
                                 bxs, bys, bms):
                        (params, rest, opt_state, streak, anoms), \
                            (losses, gnorms) = scan_chunk(
                                params, rest, opt_state, streak, anoms,
                                bxs, bys, bms,
                            )
                        return (params, rest, opt_state, streak, anoms,
                                losses[-1], gnorms[-1])

                chunk_in = (rep_sh,) * n_carry + (chunk_sh,) * 3
                if audit:
                    chunk_in = chunk_in + (rep_sh,)
                chunk_jitted = jax.jit(
                    chunk_fn,
                    in_shardings=chunk_in,
                    out_shardings=(rep_sh,) * n_out,
                    donate_argnums=tuple(range(n_carry)),
                )
            return jitted, chunk_jitted

        jitted, chunk_jitted = build_programs(accum)

        # -- integrity audit state (docs/TRAINING.md "Integrity audits") --
        # chk_dev is the donated uint32 carry; the flags are device
        # residents so flipping audit on/off per dispatch never re-lands
        # a host scalar (which would retrace nothing but still costs a
        # transfer per step)
        from mmlspark_tpu.core import integrity as _integrity

        if audit:
            chk_dev = jax.device_put(jnp.zeros((), jnp.uint32), rep_sh)
            flag_on = jax.device_put(jnp.asarray(True), rep_sh)
            flag_off = jax.device_put(jnp.asarray(False), rep_sh)
        else:
            chk_dev = flag_on = flag_off = None
        audit_base: dict | None = None
        audit_buf: list[tuple] = []

        def refresh_base() -> None:
            """Host twin of the current state — the deterministic-replay
            adjudicator's known-good starting point — plus a cleared
            dispatch buffer. Refreshed after every audit (clean or not)
            so replay windows never exceed one audit interval."""
            nonlocal audit_base
            audit_base = {
                "params": jax.device_get(params),
                "rest": jax.device_get(rest),
                "opt_state": jax.device_get(opt_state),
                "streak": jax.device_get(streak_dev),
                "anoms": jax.device_get(anoms_dev),
            }
            audit_buf.clear()

        def replay_from_base():
            """Re-execute every dispatch since the last clean audit from
            the host-twin base through the SAME compiled programs;
            returns the replayed carries + a host fold of the replayed
            params/opt-state, or ``None`` when there is nothing to
            replay (no base yet, or a TP run where per-replica replay
            has no meaning)."""
            if audit_base is None or not audit_buf or cfg.param_rules:
                return None
            p = jax.device_put(audit_base["params"], rep_sh)
            r = jax.device_put(audit_base["rest"], rep_sh)
            o = jax.device_put(audit_base["opt_state"], rep_sh)
            s = jax.device_put(jnp.asarray(audit_base["streak"]), rep_sh)
            a = jax.device_put(jnp.asarray(audit_base["anoms"]), rep_sh)
            c = jax.device_put(jnp.zeros((), jnp.uint32), rep_sh)
            for entry in list(audit_buf):
                if entry[0] == "chunk":
                    stacks = tuple(
                        jax.device_put(jnp.asarray(t), chunk_sh)
                        for t in entry[1]
                    )
                    p, r, o, s, a, c, _, _ = chunk_jitted(
                        p, r, o, s, a, c, *stacks, flag_off
                    )
                else:
                    bx, by, bm = (
                        jax.device_put(jnp.asarray(t), data_sh)
                        for t in entry[1:]
                    )
                    p, r, o, s, a, c, _, _ = jitted(
                        p, r, o, s, a, c, bx, by, bm, flag_off
                    )
            fold = _integrity.tree_checksum_host(
                (jax.device_get(p), jax.device_get(o))
            )
            return p, r, o, s, a, fold

        def run_audit(at_step: int) -> None:
            """Cross-replica integrity audit: the compiled step's
            in-graph fold (``chk_dev``) is compared against a host fold
            of EVERY device's copy of params + optimizer state.
            Data-parallel replicas are bit-identical by construction
            (grads are psum'd identically everywhere), so any
            disagreement is silent data corruption or software
            nondeterminism — the replay adjudicator tells them apart by
            re-running the interval from the last known-good host twin:
            a reproducible majority means the original flip was a
            one-off (transient SDC); an unreproducible fold means the
            step program itself is nondeterministic."""
            nonlocal params, rest, opt_state, streak_dev, anoms_dev
            self.telemetry.counter("train.integrity.audits").inc()
            chk_val = int(chk_dev)
            if cfg.param_rules:
                # TP-sharded params: per-device copies are partial
                # shards with no replica redundancy to vote with; the
                # only comparable host fold is over the assembled arrays
                folds = {-1: _integrity.tree_checksum_host(
                    (jax.device_get(params), jax.device_get(opt_state))
                )}
            else:
                folds = _integrity.per_device_checksums(
                    (params, opt_state)
                )
            from collections import Counter

            counts = Counter(folds.values())
            top = max(counts.values())
            majority = min(v for v, n in counts.items() if n == top)
            divergent = sorted(d for d, v in folds.items()
                               if v != majority)
            if not divergent and majority == chk_val:
                refresh_base()
                return
            self.telemetry.counter("train.integrity.sdc_suspected").inc()
            self.recorder.record(
                "integrity.sdc_suspected", tick=at_step,
                device_checksum=chk_val, majority_checksum=majority,
                divergent_devices=[int(d) for d in divergent],
            )
            _log.warning(
                "step %d: integrity audit mismatch (in-graph fold %d, "
                "majority host fold %d, divergent device copies %s) — "
                "silent data corruption suspected",
                at_step, chk_val, majority, divergent,
            )
            if divergent and not cfg.param_rules:
                # quarantine the divergent replicas: re-replicate every
                # carry from a majority device — the same
                # revert-to-known-good move as the anomaly quarantine,
                # applied across the replica axis
                src = min(d for d, v in folds.items() if v == majority)
                p_h, r_h, o_h, s_h, a_h = _integrity.device_copy(
                    (params, rest, opt_state, streak_dev, anoms_dev),
                    src,
                )
                params = jax.device_put(p_h, rep_sh)
                rest = jax.device_put(r_h, rep_sh)
                opt_state = jax.device_put(o_h, rep_sh)
                streak_dev = jax.device_put(jnp.asarray(s_h), rep_sh)
                anoms_dev = jax.device_put(jnp.asarray(a_h), rep_sh)
                self.recorder.record(
                    "integrity.replica_quarantined", tick=at_step,
                    devices=[int(d) for d in divergent],
                    source=int(src),
                )
                _log.warning(
                    "step %d: quarantined divergent replica copies %s; "
                    "re-replicated from device %d", at_step,
                    [int(d) for d in divergent], src,
                )
            replayed = replay_from_base()
            if replayed is not None:
                p, r, o, s, a, fold = replayed
                verdict = (
                    "transient_sdc" if fold in (majority, chk_val)
                    else "software_nondeterminism"
                )
                self.telemetry.counter(
                    "train.integrity.replay_transient_sdc"
                    if verdict == "transient_sdc" else
                    "train.integrity.replay_software_nondeterminism"
                ).inc()
                entry = {
                    "step": int(at_step), "verdict": verdict,
                    "replayed_checksum": int(fold),
                    "device_checksum": int(chk_val),
                    "majority_checksum": int(majority),
                }
                self.replay_verdicts.append(entry)
                self.recorder.record(
                    "integrity.replay", tick=at_step,
                    **{k: v for k, v in entry.items() if k != "step"},
                )
                _log.warning("step %d: replay adjudication -> %s",
                             at_step, verdict)
                if verdict == "transient_sdc" and not divergent:
                    # no majority vote repaired the state (every replica
                    # copy agreed with the corrupt lineage): adopt the
                    # verified replayed state as current
                    params, rest, opt_state = p, r, o
                    streak_dev, anoms_dev = s, a
            refresh_base()

        def guarded_fire(tick: int) -> None:
            """The ``train.step`` hook + its resilience policy, fired
            BEFORE the jitted call (donated buffers survive a raised
            fault): transients are retried inside :meth:`_fire_hook`;
            RESOURCE_EXHAUSTED walks down the power-of-two accumulation
            ladder and recompiles; ``kill`` escapes — the crash drill
            the atomic checkpoint restores from."""
            nonlocal accum, jitted, chunk_jitted
            while True:
                try:
                    self._fire_hook("train.step", tick)
                    return
                except Exception as e:
                    if is_resource_exhausted(e):
                        nxt = next_accum_rung(accum, batch=batch,
                                              n_data=n_data)
                        if nxt is None:
                            raise FriendlyError(
                                f"RESOURCE_EXHAUSTED at step {tick} with "
                                f"the gradient-accumulation ladder "
                                f"exhausted (grad_accum={accum}, batch "
                                f"{batch} over {n_data} data shards) — "
                                "reduce batch_size or model size"
                            ) from e
                        accum = nxt
                        self.telemetry.gauge("train.grad_accum").set(accum)
                        self.recorder.record("degraded", tick=tick,
                                             grad_accum=accum)
                        _log.warning(
                            "step %d: RESOURCE_EXHAUSTED -> degrading to "
                            "grad_accum=%d and recompiling", tick, accum,
                        )
                        jitted, chunk_jitted = build_programs(accum)
                        continue
                    raise

        def pull_guard(b: dict, tick: int) -> dict:
            """The ``train.data`` hook: transients retried, poison
            NaN-corrupts the first float feature/label row — the
            injected stand-in for a bad gradient the quarantine must
            skip."""
            self._fire_hook("train.data", tick)
            if self._faults.poison_value("train.data", tick=tick) is None:
                return b
            b = dict(b)
            for col in ("x", "y"):
                arr = np.asarray(b[col])
                if np.issubdtype(arr.dtype, np.floating):
                    arr = np.array(arr, copy=True)
                    arr[0] = np.nan
                    b[col] = arr
                    break
            else:
                _log.warning(
                    "train.data poison skipped at step %d: no float "
                    "column to corrupt", tick,
                )
            return b

        def save_checkpoint(at_step: int) -> None:
            """Atomic checkpoint of the full resume state. Failures
            (other than the ``kill`` crash drill) are counted and
            skipped — the previous committed checkpoint stands."""
            state = {
                "params": jax.device_get(params),
                "rest": jax.device_get(rest),
                "opt_state": jax.device_get(opt_state),
                "anomaly": {
                    "streak": jax.device_get(streak_dev),
                    "total": jax.device_get(anoms_dev),
                },
            }
            meta = {
                "steps_per_epoch": steps_per_epoch,
                "history": self.restored_history + self.history,
            }
            try:
                store.save(at_step, state, meta=meta)
            except EngineKilled:
                raise  # the torn-write crash drill escapes train()
            except Exception as e:
                self.telemetry.counter("train.checkpoint_failures").inc()
                self.recorder.record("checkpoint", tick=at_step, ok=False,
                                     error=type(e).__name__)
                _log.warning("checkpoint at step %d failed (%s); previous "
                             "checkpoint stands", at_step, e)
                return
            self.telemetry.counter("train.checkpoints").inc()
            self.recorder.record("checkpoint", tick=at_step, ok=True)

        from mmlspark_tpu.data.feed import MASK_COL, batch_iterator
        from mmlspark_tpu.data.dataset import Dataset

        if audit:
            refresh_base()
        step = step0
        self._step = step
        start_epoch = step0 // steps_per_epoch
        # Mid-epoch resume: per-epoch shuffle is seed-deterministic, so
        # skipping the first (step0 % steps_per_epoch) batches reproduces the
        # exact data position the checkpoint was taken at.
        skip_in_first = step0 % steps_per_epoch
        for epoch in range(start_epoch, cfg.epochs):
            ds = Dataset({"x": x, "y": y})
            it: Iterator = batch_iterator(
                ds,
                ["x", "y"],
                batch,
                shuffle_seed=(cfg.seed + epoch) if cfg.shuffle else None,
            )
            if epoch == start_epoch and skip_in_first:
                import itertools

                it = itertools.islice(it, skip_in_first, None)
            def grouped(batches):
                buf: list = []
                for b in batches:
                    buf.append(b)
                    if len(buf) == k_steps:
                        yield buf
                        buf = []
                if buf:
                    yield buf  # epoch tail; runs through the 1-step path

            log_every = max(cfg.log_every, 1)
            # telemetry's tokens/sec figure: rows x sequence length for
            # token-sequence inputs (2-D integer batches), plain rows
            # otherwise — the throughput unit scaling work cares about
            tokens_per_step = batch * (
                x.shape[1] if np.ndim(x) == 2 else 1
            )
            for group in grouped(it):
                t_group = time.perf_counter()
                self._step = step
                audit_due = False
                if self._faults is not None:
                    group = [pull_guard(b, step + i)
                             for i, b in enumerate(group)]
                if k_steps > 1 and len(group) == k_steps:
                    guarded_fire(step)
                    stacks = tuple(
                        jax.device_put(
                            jnp.stack([jnp.asarray(b[c]) for b in group]),
                            chunk_sh,
                        )
                        for c in ("x", "y", MASK_COL)
                    )
                    if audit:
                        audit_buf.append(("chunk", tuple(
                            np.stack([np.asarray(b[c]) for b in group])
                            for c in ("x", "y", MASK_COL)
                        )))
                        due = any(
                            (s + 1) % audit_every == 0
                            for s in range(step, step + len(group))
                        )
                        (params, rest, opt_state, streak_dev, anoms_dev,
                         chk_dev, loss, gnorm) = chunk_jitted(
                            params, rest, opt_state, streak_dev,
                            anoms_dev, chk_dev, *stacks,
                            flag_on if due else flag_off,
                        )
                        audit_due = audit_due or due
                    else:
                        (params, rest, opt_state, streak_dev, anoms_dev,
                         loss, gnorm) = chunk_jitted(
                            params, rest, opt_state, streak_dev,
                            anoms_dev, *stacks,
                        )
                    if self._faults is not None:
                        cseed = self._faults.corrupt_spec("train.step",
                                                          tick=step)
                        if cseed is not None and not cfg.param_rules:
                            params, _ = _integrity.corrupt_replica(
                                params, cseed
                            )
                    n_done = len(group)
                else:
                    for i, b in enumerate(group):
                        guarded_fire(step + i)
                        bx = jax.device_put(jnp.asarray(b["x"]), data_sh)
                        by = jax.device_put(jnp.asarray(b["y"]), data_sh)
                        bm = jax.device_put(
                            jnp.asarray(b[MASK_COL]), data_sh
                        )
                        if audit:
                            audit_buf.append((
                                "single", np.asarray(b["x"]),
                                np.asarray(b["y"]),
                                np.asarray(b[MASK_COL]),
                            ))
                            due = (step + i + 1) % audit_every == 0
                            (params, rest, opt_state, streak_dev,
                             anoms_dev, chk_dev, loss, gnorm) = jitted(
                                params, rest, opt_state, streak_dev,
                                anoms_dev, chk_dev, bx, by, bm,
                                flag_on if due else flag_off,
                            )
                            audit_due = audit_due or due
                        else:
                            (params, rest, opt_state, streak_dev,
                             anoms_dev, loss, gnorm) = jitted(
                                params, rest, opt_state, streak_dev,
                                anoms_dev, bx, by, bm,
                            )
                        if self._faults is not None:
                            # the train.step silent-corruption drill: a
                            # seeded bit-flip lands in ONE device's copy
                            # of one param leaf AFTER the dispatch, so
                            # the in-graph fold precedes the flip and
                            # the next audit's host folds see it
                            cseed = self._faults.corrupt_spec(
                                "train.step", tick=step + i
                            )
                            if cseed is not None and not cfg.param_rules:
                                params, _ = _integrity.corrupt_replica(
                                    params, cseed
                                )
                    n_done = len(group)
                # log once if any step in [step, step+n) hits the cadence;
                # the fetched loss is the group's LAST step's, so label it
                # with that step (chunking coarsens cadence, never lies)
                next_log = step + (-step) % log_every
                step += n_done
                self._step = step
                if next_log < step:
                    loss_val = float(loss)
                    gnorm_val = float(gnorm)
                    # the group's dispatch+device wall, amortized per
                    # step — async dispatch means the host-side fetch of
                    # ``loss`` above is what synchronizes the clock
                    step_s = max(
                        (time.perf_counter() - t_group) / n_done, 1e-9
                    )
                    tel = self.telemetry
                    tel.histogram("train.step_ms").record(step_s * 1e3)
                    tel.histogram("train.tokens_per_sec").record(
                        tokens_per_step / step_s
                    )
                    # a quarantined step's loss/gnorm is non-finite by
                    # definition — keep it out of the log-bucketed
                    # histograms (history and the anomaly counters carry
                    # the honest record)
                    if np.isfinite(loss_val):
                        tel.histogram("train.loss").record(loss_val)
                    if np.isfinite(gnorm_val):
                        tel.histogram("train.grad_norm").record(gnorm_val)
                    self.history.append(
                        {"step": step - 1, "epoch": epoch, "loss": loss_val,
                         "grad_norm": gnorm_val}
                    )
                    self.recorder.record(
                        "step", tick=step - 1, epoch=epoch, loss=loss_val,
                        grad_norm=gnorm_val,
                    )
                    _log.info(
                        "step %d epoch %d loss %.5f grad_norm %.4f "
                        "step_ms %.1f", step - 1, epoch, loss_val,
                        gnorm_val, step_s * 1e3,
                    )
                    # anomaly accounting rides the log-cadence sync the
                    # loss fetch above already paid for: the quarantine
                    # itself is in-graph; the host only reads the
                    # counters here, so the N-consecutive abort lags the
                    # Nth bad step by < log_every steps
                    self._check_anomalies(streak_dev, anoms_dev,
                                          seen_anoms, step - 1)
                    seen_anoms = max(seen_anoms, int(anoms_dev))
                if audit and audit_due:
                    # the interval's ONE audit host sync: read the
                    # in-graph fold and every replica's copy, adjudicate
                    # (runs BEFORE the checkpoint save so a detected
                    # corruption never gets committed to disk)
                    run_audit(step - 1)
                if (
                    store is not None
                    and cfg.checkpoint_every
                    # any step of the finished group on the save cadence
                    # triggers a save of the current (group-end) state —
                    # with chunked dispatch the exact cadence step has no
                    # materialized state of its own
                    and any(
                        s % cfg.checkpoint_every == 0
                        for s in range(step - n_done, step)
                    )
                ):
                    # gate BEFORE fetching: save_checkpoint device_gets
                    # the whole (possibly TP-sharded) state, which would
                    # stall async dispatch on every non-checkpoint step
                    save_checkpoint(step - 1)
            if eval_fn is not None:
                variables = _merge_variables(
                    jax.device_get(params), jax.device_get(rest)
                )
                metrics = eval_fn(variables)
                self.history.append({"step": step, "epoch": epoch, **metrics})

        # end-of-run anomaly sweep: catches a terminal bad streak that
        # never crossed a log-cadence sync point
        self._check_anomalies(streak_dev, anoms_dev, seen_anoms, step - 1)
        seen_anoms = max(seen_anoms, int(anoms_dev))
        if store is not None and store.latest_step() != step - 1:
            save_checkpoint(step - 1)
        final_loss = next(
            (h["loss"] for h in reversed(self.history) if "loss" in h), None
        )
        _log.info("training done: %d steps, final logged loss %s", step,
                  final_loss)
        return _merge_variables(jax.device_get(params), jax.device_get(rest))

    def _check_anomalies(self, streak_dev, anoms_dev, seen_anoms: int,
                         at_step: int) -> None:
        """Host-side read of the in-graph anomaly carries: sync the
        skipped-step counter and abort on a streak past the limit."""
        cfg = self.config
        streak_val = int(streak_dev)
        anoms_val = int(anoms_dev)
        if anoms_val > seen_anoms:
            self.telemetry.counter("train.anomalies_skipped").inc(
                anoms_val - seen_anoms
            )
            self.recorder.record(
                "anomaly", tick=at_step, streak=streak_val,
                skipped_total=anoms_val,
            )
            _log.warning(
                "step %d: %d anomalous gradient step(s) quarantined "
                "(streak %d) — params/optimizer not advanced",
                at_step, anoms_val - seen_anoms, streak_val,
            )
        if cfg.anomaly_limit and streak_val >= cfg.anomaly_limit:
            raise FriendlyError(
                f"{streak_val} consecutive anomalous gradient steps "
                f"(non-finite or exploding grad_norm) at step {at_step}; "
                f"aborting after anomaly_limit={cfg.anomaly_limit}. The "
                "quarantine kept params and optimizer state at their "
                "last healthy values — inspect the dumped flight "
                "recorder and the train.data pipeline"
            )
