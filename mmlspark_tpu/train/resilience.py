"""Training resilience primitives: the atomic checkpoint store and the
gradient-accumulation degrade ladder (docs/TRAINING.md).

The serving side proved the protocol first (PR 7's ``serve.snapshot``
hook and the engine's keep-the-previous-snapshot rule); this module is
the training-side twin. Orbax already writes its own payload atomically
(temp dir + finalize rename), but a training checkpoint is MORE than
the orbax payload: the step count, the loss history, the anomaly
streak, and the data-epoch geometry must commit in the same instant or
a resume can pair new arrays with a stale cursor. The
:class:`AtomicCheckpointStore` therefore layers a manifest commit on
top of orbax:

1. the array payload is written to ``payload-<step>.tmp`` (orbax's own
   internal atomicity applies inside that directory),
2. the ``train.checkpoint`` fault hook fires — the drill window where a
   torn write is injected,
3. the payload directory is renamed to its final ``payload-<step>``
   name,
4. the manifest (step + JSON meta sidecar: history, streak, counters,
   ``steps_per_epoch``) is written to a temp file and ``os.replace``\\ d
   to ``step-<step>.json`` — the COMMIT POINT.

A checkpoint exists iff its manifest AND its payload directory both
exist; anything else (a ``.tmp`` payload, a payload without a
manifest) is torn debris that :meth:`AtomicCheckpointStore.steps`
ignores and the next save sweeps, so a crash at ANY point leaves the
previous complete checkpoint restorable — the property the
torn-checkpoint drill in ``tests/test_train_resilience.py`` pins.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Callable

from mmlspark_tpu.core.exceptions import FriendlyError
from mmlspark_tpu.core.integrity import CheckpointCorruption, dir_sha256
from mmlspark_tpu.core.logging_utils import get_logger

_log = get_logger("train.resilience")

_MANIFEST_RE = re.compile(r"^step-(\d+)\.json$")
_PAYLOAD_RE = re.compile(r"^payload-(\d+)$")


class AtomicCheckpointStore:
    """Manifest-committed checkpoint store over orbax.

    ``pre_commit(step)`` — when given — is called between the payload
    write and the manifest commit; the trainer wires the
    ``train.checkpoint`` fault hook there so an injected ``kill``
    models a mid-write crash: the payload (or its ``.tmp``) is on disk
    but no manifest references it, and the store still reports the
    previous step as latest.

    ``post_hash(step, payload_dir)`` — when given — is called AFTER
    the payload sha256 is computed but before the commit: the silent-
    corruption drill window. The trainer wires the ``train.checkpoint``
    ``corrupt`` fault kind there, so an injected bit-flip lands in a
    payload whose manifest commits the PRE-flip hash — exactly the
    at-rest corruption :meth:`restore` must detect
    (:class:`~mmlspark_tpu.core.integrity.CheckpointCorruption`).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 pre_commit: Callable[[int], None] | None = None,
                 post_hash: Callable[[int, str], None] | None = None):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max(int(max_to_keep), 1)
        self.pre_commit = pre_commit
        self.post_hash = post_hash
        self._ckptr = None  # lazy orbax StandardCheckpointer
        os.makedirs(self.directory, exist_ok=True)

    def _checkpointer(self):
        if self._ckptr is None:
            import orbax.checkpoint as ocp

            self._ckptr = ocp.StandardCheckpointer()
        return self._ckptr

    # -- layout -------------------------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step-{step}.json")

    def _payload_path(self, step: int) -> str:
        return os.path.join(self.directory, f"payload-{step}")

    # -- inventory ----------------------------------------------------------

    def steps(self) -> list[int]:
        """Committed steps, ascending: manifest AND payload both
        present — a manifest whose payload vanished (or the reverse) is
        a torn write and does not count."""
        have_manifest = set()
        have_payload = set()
        for name in os.listdir(self.directory):
            m = _MANIFEST_RE.match(name)
            if m:
                have_manifest.add(int(m.group(1)))
                continue
            m = _PAYLOAD_RE.match(name)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                have_payload.add(int(m.group(1)))
        return sorted(have_manifest & have_payload)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    # -- save / restore ------------------------------------------------------

    def save(self, step: int, state: dict, *,
             meta: dict[str, Any] | None = None) -> None:
        """Write ``state`` (a pytree of host arrays) + ``meta`` (JSON)
        as checkpoint ``step``. Atomic: until the final manifest
        ``os.replace`` lands, :meth:`latest_step` still names the
        previous checkpoint."""
        import jax
        import numpy as np

        step = int(step)
        final = self._payload_path(step)
        tmp = final + ".tmp"
        # sweep debris from a previous torn attempt at this step
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        # orbax rejects bare python/numpy scalars (optimizer step
        # counts device_get to 0-d values): coerce every leaf to an
        # ndarray first
        state = jax.tree_util.tree_map(np.asarray, state)
        ckptr = self._checkpointer()
        ckptr.save(tmp, state)
        # StandardCheckpointer finalizes (its own internal tmp-dir
        # rename) on a background thread; the payload is only complete
        # once that commit lands, and our manifest must never reference
        # a payload orbax is still writing
        ckptr.wait_until_finished()
        # payload hash taken at PRODUCTION time: anything that changes
        # the bytes after this line (the post_hash corrupt drill, a
        # genuine at-rest flip) is detectable on restore
        payload_sha = dir_sha256(tmp)
        if self.post_hash is not None:
            # the silent-corruption drill window: a bit-flip here lands
            # in a payload whose manifest commits the pre-flip hash
            self.post_hash(step, tmp)
        if self.pre_commit is not None:
            # the torn-write drill window: a raise here leaves the
            # payload uncommitted and the previous checkpoint intact
            self.pre_commit(step)
        if os.path.isdir(final):
            # re-save of an already-committed step (same deterministic
            # state): replace the payload in place
            shutil.rmtree(final)
        os.rename(tmp, final)
        manifest = {
            "format": 1,
            "step": step,
            "payload": os.path.basename(final),
            "payload_sha256": payload_sha,
            "meta": meta or {},
        }
        mtmp = self._manifest_path(step) + ".tmp"
        with open(mtmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=1)
        os.replace(mtmp, self._manifest_path(step))  # COMMIT POINT
        self._prune()

    def restore(self, target: dict, *,
                step: int | None = None) -> tuple[dict, dict, int]:
        """Restore ``(state, meta, step)`` for ``step`` (default: the
        latest committed checkpoint). ``target`` shapes/dtypes the
        orbax restore so the state comes back exactly as saved.

        Verified restore (docs/TRAINING.md "Integrity audits"): when
        the manifest committed a ``payload_sha256``, the payload bytes
        are re-hashed BEFORE orbax reads them; a mismatch quarantines
        the step (manifest renamed to ``.corrupt`` — preserved as
        evidence, invisible to :meth:`steps`) and raises
        :class:`~mmlspark_tpu.core.integrity.CheckpointCorruption`
        naming both hashes, so the caller's retry lands on the
        previous committed checkpoint."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FriendlyError(
                    f"no committed checkpoint in {self.directory!r} "
                    "(torn payloads without a manifest do not count)"
                )
        if step not in self.steps():
            raise FriendlyError(
                f"checkpoint step {step} is not committed in "
                f"{self.directory!r}; committed steps: {self.steps()}"
            )
        with open(self._manifest_path(step), encoding="utf-8") as f:
            manifest = json.load(f)
        expected = manifest.get("payload_sha256")
        if expected is not None:
            actual = dir_sha256(self._payload_path(step))
            if actual != expected:
                self._quarantine(int(step))
                raise CheckpointCorruption(
                    int(step), expected=expected, actual=actual
                )
        state = self._checkpointer().restore(
            self._payload_path(step), target
        )
        return state, manifest.get("meta", {}), int(step)

    def _quarantine(self, step: int) -> None:
        """Demote a corrupt checkpoint: the manifest renames to
        ``.corrupt`` (kept for post-mortems; ``steps()`` no longer
        counts the step) so the previous committed checkpoint becomes
        latest."""
        path = self._manifest_path(step)
        try:
            os.replace(path, path + ".corrupt")
        except OSError:  # pragma: no cover - quarantine is best-effort
            _log.warning("could not quarantine corrupt checkpoint %d",
                         step)
        _log.warning(
            "checkpoint step %d failed payload verification and was "
            "quarantined; latest committed step is now %s",
            step, self.latest_step(),
        )

    # -- retention -----------------------------------------------------------

    def _prune(self) -> None:
        """Keep the newest ``max_to_keep`` committed checkpoints.
        Manifest removed FIRST so a crash mid-prune degrades a
        checkpoint to torn (ignored) rather than leaving a manifest
        pointing at a deleted payload that :meth:`steps` would have to
        special-case."""
        steps = self.steps()
        for old in steps[:-self.max_to_keep]:
            try:
                os.remove(self._manifest_path(old))
                shutil.rmtree(self._payload_path(old),
                              ignore_errors=True)
            except OSError:  # pragma: no cover - best-effort retention
                _log.warning("could not prune checkpoint %d", old)


def next_accum_rung(accum: int, *, batch: int, n_data: int) -> int | None:
    """Next power-of-two gradient-accumulation rung after ``accum``
    that still divides the (data-axis rounded) ``batch``, or ``None``
    when the ladder is exhausted (the micro-batch is already one row
    per data shard). The trainer walks this on ``RESOURCE_EXHAUSTED``:
    same optimizer semantics, activations for ``1/accum`` of the batch
    live at once (docs/TRAINING.md "The accumulation ladder")."""
    limit = batch // max(n_data, 1)
    nxt = max(int(accum), 1) * 2
    while nxt <= limit:
        if batch % (nxt * n_data) == 0:
            return nxt
        nxt *= 2
    return None
