"""Synthetic-data training demo — the ``train`` subcommand's body and
``bench.py``'s ``train_resilience`` helpers.

Trains a small MLP classifier on seeded synthetic float blobs through
:class:`~mmlspark_tpu.train.trainer.SPMDTrainer`, mirroring the serve
demo's contract: ONE parseable JSON line out, carrying the trainer's
step-time/loss/grad-norm histograms, the resilience counters
(``train.retries_total``, ``train.anomalies_skipped``,
``train.checkpoints``, ``train.checkpoint_failures``), and the run's
checkpoint/restart summary. The demo owns the restart control loop a
fleet supervisor would run: an injected ``kill``
(``--faults 'train.step:kill=...'`` or a schedule) crashes the
trainer, and the demo rebuilds it to resume from the last atomically
committed checkpoint — bit-exact, per the drill tests.

Float features on purpose: ``train.data`` poison NaN-corrupts a
feature row, which is what drives the grad-anomaly quarantine
(docs/TRAINING.md "Anomaly policy"). With ``telemetry_dir`` set (the
CLI's ``--telemetry-dir``), the flight-recorder timeline lands in
``events.jsonl``, the metrics dict in ``metrics.json``, and the
Prometheus text exposition in ``metrics.prom`` — the schema
``tools/check_metrics_schema.py --train`` gates.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np


def run_train_demo(*, epochs: int = 2, batch_size: int = 32,
                   n_samples: int = 192, features: int = 8,
                   classes: int = 2, hidden: tuple = (16,),
                   seed: int = 0, log_every: int = 1,
                   checkpoint_every: int = 1, max_restarts: int = 5,
                   anomaly_limit: int = 5, max_grad_norm: float = 0.0,
                   audit_every: int = 0,
                   mesh: str | None = None,
                   checkpoint_dir: str | None = None,
                   telemetry_dir: str | None = None,
                   faults: str | None = None) -> dict:
    """Run the synthetic training loop (with crash-restart supervision);
    returns the metrics dict the CLI prints as its one JSON line."""
    from mmlspark_tpu.core.faults import EngineKilled, parse_fault_spec
    from mmlspark_tpu.core.telemetry import FlightRecorder, MetricRegistry
    from mmlspark_tpu.parallel.mesh import parse_mesh_axes
    from mmlspark_tpu.models import build_model
    from mmlspark_tpu.train.resilience import AtomicCheckpointStore
    from mmlspark_tpu.train.trainer import SPMDTrainer, TrainConfig

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n_samples, features)).astype(np.float32)
    w = rng.normal(size=(features, classes)).astype(np.float32)
    y = np.argmax(
        x @ w + 0.1 * rng.normal(size=(n_samples, classes)), axis=1
    )
    graph = build_model("mlp", num_outputs=classes, hidden=tuple(hidden))

    # the kill-restart drill needs somewhere durable to resume from even
    # when the caller didn't ask to keep checkpoints
    ckpt_dir = checkpoint_dir or tempfile.mkdtemp(prefix="mmltpu-train-ck-")
    cfg = TrainConfig(
        epochs=epochs, batch_size=batch_size, learning_rate=1e-2,
        seed=seed, log_every=log_every, shuffle=False,
        mesh_axes=parse_mesh_axes(mesh) if mesh else None,
        checkpoint_dir=ckpt_dir, checkpoint_every=checkpoint_every,
        anomaly_limit=anomaly_limit, max_grad_norm=max_grad_norm,
        retry_backoff_s=0.0, audit_every=audit_every,
    )
    # ONE registry + recorder + injector across restarts: the resumed
    # trainer keeps appending to the same timeline, and the injector's
    # remaining schedule/rate stream carries over (a respawned process
    # doesn't reset the world's faults)
    registry = MetricRegistry()
    recorder = FlightRecorder()
    injector = parse_fault_spec(faults) if faults else None
    if injector is not None and injector.listener is None:
        def _on_fault(kind: str, site: str) -> None:
            registry.counter("train.faults_injected_total").inc()
            recorder.record("fault_injected", kind=kind, site=site)
        injector.listener = _on_fault

    restarts = 0
    while True:
        trainer = SPMDTrainer(graph, cfg, telemetry=registry,
                              recorder=recorder, faults=injector)
        try:
            trainer.train(x, y)
            break
        except EngineKilled:
            # the crash drill: rebuild the trainer and resume from the
            # last committed checkpoint — the supervisor loop a real
            # preemption would trigger
            restarts += 1
            recorder.record("restart", attempt=restarts)
            if restarts >= max_restarts:
                raise

    full_history = trainer.restored_history + trainer.history
    loss_hist = [h for h in full_history if "loss" in h]
    out = registry.to_dict()
    out.update(
        steps_total=(loss_hist[-1]["step"] + 1) if loss_hist else 0,
        final_loss=loss_hist[-1]["loss"] if loss_hist else None,
        restarts=restarts,
        epochs=epochs,
        batch_size=batch_size,
        history_len=len(full_history),
        checkpoint_steps=AtomicCheckpointStore(ckpt_dir).steps(),
        checkpoint_dir=ckpt_dir,
        model_config={"features": features, "classes": classes,
                      "hidden": list(hidden)},
        audit_every=audit_every,
        replay_verdicts=trainer.replay_verdicts,
    )
    if injector is not None:
        out["faults_injected"] = dict(injector.counts)
    if telemetry_dir:
        from mmlspark_tpu.core.telemetry import (
            atomic_write_json, atomic_write_text,
        )

        # same tmp-file + os.replace commit point as the checkpoint
        # store: a kill mid-dump never leaves a torn telemetry file
        os.makedirs(telemetry_dir, exist_ok=True)
        recorder.dump(os.path.join(telemetry_dir, "events.jsonl"))
        atomic_write_json(
            os.path.join(telemetry_dir, "metrics.json"), out,
            indent=1, default=str,
        )
        atomic_write_text(
            os.path.join(telemetry_dir, "metrics.prom"),
            registry.to_prometheus(),
        )
    return out
