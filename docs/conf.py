# Sphinx configuration for environments that have sphinx installed
# (this zero-egress build image does not — tools/docgen.py renders the
# same generated .rst tree to static HTML instead; reference analog:
# tools/pydocs assembling the codegen output).
project = "mmlspark-tpu"
author = "mmlspark-tpu developers"
extensions: list[str] = []
master_doc = "index"
exclude_patterns = ["html"]
html_theme = "alabaster"
